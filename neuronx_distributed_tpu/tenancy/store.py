"""Paged LoRA adapter store — many tenants behind one compiled serving
envelope (S-LoRA, Sheng et al. 2023: thousands of adapters share a base
model by paging adapter weights through the same unified memory machinery
as the KV cache).

Two halves, split exactly like the paged KV cache:

- :class:`AdapterLayout` — the STATIC flattening contract.  An adapter's
  per-layer low-rank factors (``a_q [H, r]``, ``b_q [r, NQ*D]``, ``a_v``,
  ``b_v`` — the standard q/v LoRA pair ``peft.py`` trains) are flattened
  into fixed-size pages of one flat fp32 device pool ``[num_pages,
  page_elems]``; the layout's static offsets are what the compiled decode
  program slices the gathered flat view back into factors with (one
  program serves every adapter — the offsets are shapes, not data).

- :class:`AdapterStore` — the HOST-side residency manager over the same
  refcounted :class:`~..kvcache.allocator.BlockAllocator` the KV pool
  uses: ``register`` keeps a host copy of the flattened blocks, ``acquire``
  pins a request's adapter at admission (allocating + device-loading its
  pages on a cold start, LRU-evicting unpinned adapters to make room),
  ``release`` drops the pin on every terminal state.  Hot adapters stay
  resident across requests (an acquire of a resident adapter is a pure
  refcount bump — ``tenancy/adapter_hits_total``); cold ones cost a page
  load (``tenancy/adapter_loads_total``).  Page 0 is the allocator's NULL
  page and its device content is all zeros — which, for a zero-initialized
  low-rank delta, IS the identity: adapter 0 ("no adapter") needs no
  store entry, no pages and no special-casing in the compiled program.

Acquire is transactional exactly like ``PagedKVManager.admit_slot``: the
``tenancy/adapter_load`` fault point sits mid-acquire, and any failure
releases every page taken before re-raising — a crashed admission leaks
nothing (the chaos tests pin this).
"""

from __future__ import annotations

import dataclasses
import math
import re
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from neuronx_distributed_tpu.kvcache.allocator import (
    NULL_PAGE,
    BlockAllocator,
    PoolExhausted,
)
from neuronx_distributed_tpu.resilience.faults import fault_point
from neuronx_distributed_tpu.utils.logger import get_logger

logger = get_logger(__name__)

# registry contract (obs.schemas.REGISTRY_METRICS)
ADAPTERS_RESIDENT = "tenancy/adapters_resident"
ADAPTER_POOL_PAGES_IN_USE = "tenancy/adapter_pool_pages_in_use"
ADAPTER_HITS_TOTAL = "tenancy/adapter_hits_total"
ADAPTER_LOADS_TOTAL = "tenancy/adapter_loads_total"
ADAPTER_EVICTIONS_TOTAL = "tenancy/adapter_evictions_total"

# factor names in canonical order — the layout's flattening order and the
# tuple order the model's adapter kwarg consumes, in one place
FACTOR_NAMES = ("a_q", "b_q", "a_v", "b_v")

_LAYER_RE = re.compile(r"(?:^|_)layer_?(\d+)$")


@dataclasses.dataclass(frozen=True)
class AdapterLayout:
    """Static flattening contract between the store and the compiled
    multi-adapter decode program.

    ``rank`` is the POOL rank: every registered adapter's factors are
    zero-padded up to it (padding columns of A / rows of B contribute
    exact zeros), so adapters of any rank ``<= rank`` co-batch through one
    compiled program.  ``page_elems`` is the flat page width in fp32
    elements — the paging granularity the :class:`BlockAllocator`
    refcounts."""

    num_layers: int
    hidden_size: int
    q_out: int   # num_heads * head_dim
    v_out: int   # num_kv_heads * head_dim
    rank: int
    page_elems: int = 2048

    def __post_init__(self):
        if self.rank < 1:
            raise ValueError(f"pool rank must be >= 1, got {self.rank}")
        if self.page_elems < 1:
            raise ValueError(
                f"page_elems must be >= 1, got {self.page_elems}")

    @staticmethod
    def for_model(model: Any, rank: int,
                  page_elems: int = 2048) -> "AdapterLayout":
        """Layout for a serving wrapper's module config (the
        ``ParallelInferenceModel`` the engine compiles)."""
        cfg = model.module.config
        return AdapterLayout(
            num_layers=cfg.num_layers, hidden_size=cfg.hidden_size,
            q_out=cfg.num_heads * cfg.head_dim_,
            v_out=cfg.num_kv_heads * cfg.head_dim_,
            rank=rank, page_elems=page_elems)

    def factor_shapes(self) -> List[Tuple[str, Tuple[int, int]]]:
        """One layer's ``(name, shape)`` list in canonical order."""
        r, h = self.rank, self.hidden_size
        return [("a_q", (h, r)), ("b_q", (r, self.q_out)),
                ("a_v", (h, r)), ("b_v", (r, self.v_out))]

    @property
    def layer_elems(self) -> int:
        return sum(s[0] * s[1] for _, s in self.factor_shapes())

    @property
    def total_elems(self) -> int:
        return self.num_layers * self.layer_elems

    @property
    def pages_per_adapter(self) -> int:
        return math.ceil(self.total_elems / self.page_elems)

    def layer_entries(self) -> List[List[Tuple[str, int, Tuple[int, int]]]]:
        """Per layer, the ``(name, flat_offset, shape)`` slice plan the
        compiled gather carves the flat ``[B, AP * page_elems]`` view
        with."""
        out = []
        off = 0
        for _ in range(self.num_layers):
            entries = []
            for name, shape in self.factor_shapes():
                entries.append((name, off, shape))
                off += shape[0] * shape[1]
            out.append(entries)
        return out

    def flatten(self, factors: Sequence[Dict[str, np.ndarray]],
                alpha: float) -> np.ndarray:
        """Flatten per-layer factor dicts into the padded page blocks
        ``[pages_per_adapter, page_elems]`` fp32.

        Each layer dict holds ``a_q``/``b_q``/``a_v``/``b_v`` (b factors
        may arrive ``[r, n_heads, head_dim]`` as the ``peft`` modules store
        them, or pre-reshaped ``[r, out]``); ranks ``<= rank`` are
        zero-padded, and the LoRA scale ``alpha / r`` is folded into the b
        factors here so the device math is a bare einsum pair (``alpha``
        must equal the adapters' ``lora_alpha`` — the same contract as
        ``peft.merge_lora``)."""
        if len(factors) != self.num_layers:
            raise ValueError(
                f"adapter has {len(factors)} layers, layout expects "
                f"{self.num_layers}")
        flat = np.zeros((self.pages_per_adapter * self.page_elems,),
                        np.float32)
        for layer, entries in zip(factors, self.layer_entries()):
            missing = [n for n, _, _ in entries if n not in layer]
            if missing:
                raise ValueError(
                    f"adapter layer missing factors {missing} "
                    f"(present: {sorted(layer)})")
            r_a = None
            for name, off, shape in entries:
                arr = np.asarray(layer[name], np.float32)
                if arr.ndim == 3:  # [r, n_heads, head_dim] module layout
                    arr = arr.reshape(arr.shape[0], -1)
                if arr.ndim != 2:
                    raise ValueError(
                        f"factor {name} must be 2-D (or the module's 3-D "
                        f"[r, heads, dim]), got shape {arr.shape}")
                ra = arr.shape[1] if name.startswith("a_") else arr.shape[0]
                if r_a is None:
                    r_a = ra
                elif ra != r_a:
                    raise ValueError(
                        f"factor {name} rank {ra} != layer rank {r_a}")
                if ra > self.rank:
                    raise ValueError(
                        f"adapter rank {ra} exceeds pool rank {self.rank}")
                want = ((shape[0], ra) if name.startswith("a_")
                        else (ra, shape[1]))
                if arr.shape != want:
                    raise ValueError(
                        f"factor {name} shape {arr.shape} != expected "
                        f"{want} (layout {shape}, adapter rank {ra})")
                padded = np.zeros(shape, np.float32)
                if name.startswith("a_"):
                    padded[:, :ra] = arr
                else:
                    padded[:ra, :] = (alpha / ra) * arr
                flat[off:off + shape[0] * shape[1]] = padded.reshape(-1)
        return flat.reshape(self.pages_per_adapter, self.page_elems)


def factors_from_params(params: Any) -> List[Dict[str, np.ndarray]]:
    """Extract the q/v LoRA factors per layer from a trained LoRA params
    pytree (the tree ``peft.lora_params`` prunes): leaves named
    ``lora_a_q`` / ``lora_b_q`` / ``lora_a_v`` / ``lora_b_v`` under a
    ``layer_<i>`` path component, however deeply nested or wrapped the
    surrounding tree is.  Returns the per-layer dict list
    :meth:`AdapterLayout.flatten` consumes."""
    import jax

    from neuronx_distributed_tpu.peft import lora_params

    pruned = lora_params(params)
    found: Dict[int, Dict[str, np.ndarray]] = {}
    for path, leaf in jax.tree_util.tree_flatten_with_path(pruned)[0]:
        if leaf is None:
            continue
        keys = [str(getattr(k, "key", k)) for k in path]
        name = None
        for k in keys:
            if k.startswith("lora_") and k[len("lora_"):] in FACTOR_NAMES:
                name = k[len("lora_"):]
        if name is None:
            continue
        layer = None
        for k in keys:
            m = _LAYER_RE.search(k)
            if m:
                layer = int(m.group(1))
        if layer is None:
            raise ValueError(
                f"LoRA leaf {'/'.join(keys)} has no layer_<i> path "
                "component; per-layer named trees are required (unstack "
                "scan_layers checkpoints first)")
        found.setdefault(layer, {})[name] = np.asarray(leaf)
    if not found:
        raise ValueError(
            "no lora_{a,b}_{q,v} leaves found: the adapter tree carries no "
            "q/v LoRA factors (was the model built with lora_targets "
            "including 'qkv'?)")
    layers = sorted(found)
    if layers != list(range(len(layers))):
        raise ValueError(f"non-contiguous adapter layers: {layers}")
    return [found[i] for i in layers]


class AdapterStore:
    """Refcounted paged residency for registered LoRA adapters.

    ``registry`` (an ``obs.MetricRegistry``) may be attached at
    construction or later via :meth:`attach_registry` (the serving engine
    attaches its own).  Adapter id 0 is RESERVED — it means "no adapter"
    and is served by the pool's zero NULL page, so it can never be
    registered."""

    def __init__(self, layout: AdapterLayout, num_pages: int,
                 registry: Any = None):
        if layout.pages_per_adapter > num_pages - 1:
            raise ValueError(
                f"one adapter needs {layout.pages_per_adapter} pages but "
                f"the pool holds only {num_pages - 1} allocatable pages "
                "(page 0 is the NULL page); grow num_pages or page_elems")
        self.layout = layout
        self.num_pages = num_pages
        self.alloc = BlockAllocator(num_pages)
        self._blocks: Dict[int, np.ndarray] = {}   # host copy, survives evict
        self._resident: Dict[int, List[int]] = {}  # aid -> physical pages
        self._last_used: Dict[int, int] = {}
        self._clock = 0
        self.registry = None
        if registry is not None:
            self.attach_registry(registry)

    # -- wiring ------------------------------------------------------------

    def attach_registry(self, registry: Any) -> None:
        self.registry = registry
        registry.gauge(ADAPTERS_RESIDENT)
        registry.gauge(ADAPTER_POOL_PAGES_IN_USE)
        for c in (ADAPTER_HITS_TOTAL, ADAPTER_LOADS_TOTAL,
                  ADAPTER_EVICTIONS_TOTAL):
            registry.counter(c)

    # -- registration ------------------------------------------------------

    def register(self, adapter_id: int, adapter: Any,
                 alpha: float = 16.0) -> None:
        """Register an adapter under ``adapter_id`` (> 0).  ``adapter`` is
        a trained LoRA params pytree (``peft``-style ``lora_{a,b}_{q,v}``
        leaves under ``layer_<i>``) or a per-layer list of
        ``{"a_q", "b_q", "a_v", "b_v"}`` factor dicts; ``alpha`` must
        equal the adapters' ``lora_alpha``.  Registration is host-only —
        device pages are paid lazily at the first :meth:`acquire`."""
        adapter_id = int(adapter_id)
        if adapter_id < 1:
            raise ValueError(
                f"adapter_id must be >= 1 (0 is the reserved no-adapter "
                f"identity), got {adapter_id}")
        if adapter_id in self._blocks:
            raise ValueError(f"adapter {adapter_id} already registered")
        factors = (list(adapter) if isinstance(adapter, (list, tuple))
                   else factors_from_params(adapter))
        self._blocks[adapter_id] = self.layout.flatten(factors, alpha)

    def registered(self, adapter_id: int) -> bool:
        return adapter_id == 0 or adapter_id in self._blocks

    def resident_ids(self) -> frozenset:
        """Adapters whose pages are device-resident right now — the fleet
        router's adapter-affinity evidence."""
        return frozenset(self._resident)

    # -- residency (pin-at-admission / release-on-terminal) ----------------

    def acquire(self, adapter_id: int,
                engine_step: int = 0) -> List[Tuple[int, np.ndarray]]:
        """Pin ``adapter_id`` for one request.  Returns the device loads
        the caller must perform — ``[(phys_page, host_block), ...]`` — on a
        cold start, or ``[]`` when the adapter is already resident (or is
        adapter 0).  Transactional: any failure mid-acquire releases every
        page taken before re-raising."""
        if adapter_id == 0:
            return []
        blocks = self._blocks.get(adapter_id)
        if blocks is None:
            raise KeyError(f"adapter {adapter_id} is not registered")
        self._clock += 1
        self._last_used[adapter_id] = self._clock
        pages = self._resident.get(adapter_id)
        if pages is not None:
            for p in pages:
                self.alloc.retain(p)
            if self.registry is not None:
                self.registry.counter(ADAPTER_HITS_TOTAL).inc()
            return []
        need = self.layout.pages_per_adapter
        self._ensure_free(need)
        pages = self.alloc.alloc(need)  # atomic: PoolExhausted takes nothing
        try:
            # chaos hook: a crash between allocation and the pin must leak
            # nothing (tests/test_tenancy.py)
            fault_point("tenancy/adapter_load", adapter_id=adapter_id,
                        engine_step=engine_step)
            for p in pages:
                self.alloc.retain(p)  # the request's pin atop the store ref
        except BaseException:
            for p in pages:
                self.alloc.free(p)
            raise
        self._resident[adapter_id] = pages
        if self.registry is not None:
            self.registry.counter(ADAPTER_LOADS_TOTAL).inc()
        return [(phys, blocks[i]) for i, phys in enumerate(pages)]

    def release(self, adapter_id: int) -> None:
        """Drop one request's pin.  The adapter stays resident (store-owned
        reference) until LRU eviction needs its pages."""
        if adapter_id == 0:
            return
        pages = self._resident.get(adapter_id)
        if pages is None:
            raise ValueError(
                f"release of non-resident adapter {adapter_id}")
        for p in pages:
            self.alloc.free(p)

    def table(self, adapter_id: int) -> np.ndarray:
        """The adapter's ``[pages_per_adapter]`` int32 physical page map
        (all-NULL for adapter 0) — the per-slot row of the compiled
        decode's adapter block table."""
        if adapter_id == 0:
            return np.full((self.layout.pages_per_adapter,), NULL_PAGE,
                           np.int32)
        return np.asarray(self._resident[adapter_id], np.int32)

    def pins(self, adapter_id: int) -> int:
        """Active request pins on a resident adapter (0 when merely
        resident: the store's own reference does not count)."""
        pages = self._resident.get(adapter_id)
        if not pages:
            return 0
        return self.alloc.refcount(pages[0]) - 1

    # -- capacity ----------------------------------------------------------

    @property
    def capacity(self) -> int:
        return self.alloc.capacity

    def evictable_pages(self) -> int:
        return sum(len(pages) for aid, pages in self._resident.items()
                   if self.pins(aid) == 0)

    def pages_free(self) -> int:
        return self.alloc.free_count + self.evictable_pages()

    def _ensure_free(self, n: int) -> None:
        """LRU-evict unpinned resident adapters until ``n`` pages are free
        (host accounting only — the evicted pages' device content is
        simply overwritten by the next load)."""
        while self.alloc.free_count < n:
            cold = [aid for aid in self._resident if self.pins(aid) == 0]
            if not cold:
                raise PoolExhausted(
                    f"adapter pool exhausted: need {n} pages, "
                    f"{self.alloc.free_count} free and every resident "
                    "adapter is pinned; retry after requests drain or grow "
                    "the pool")
            victim = min(cold, key=lambda aid: self._last_used.get(aid, 0))
            for p in self._resident.pop(victim):
                self.alloc.free(p)
            if self.registry is not None:
                self.registry.counter(ADAPTER_EVICTIONS_TOTAL).inc()
            logger.info("tenancy: evicted cold adapter %d (%d pages)",
                        victim, self.layout.pages_per_adapter)

    # -- telemetry / invariants --------------------------------------------

    def export_gauges(self) -> None:
        if self.registry is None:
            return
        self.registry.gauge(ADAPTERS_RESIDENT).set(len(self._resident))
        self.registry.gauge(ADAPTER_POOL_PAGES_IN_USE).set(self.alloc.in_use)

    def assert_invariants(self) -> None:
        """Allocator invariants plus the residency contract: resident
        adapters own disjoint allocated pages (refcount = 1 store ref +
        pins), every resident id is registered, and nothing else holds
        pool pages."""
        self.alloc.assert_invariants()
        seen: set = set()
        for aid, pages in self._resident.items():
            assert aid in self._blocks, f"resident unregistered adapter {aid}"
            assert len(pages) == self.layout.pages_per_adapter
            refs = {self.alloc.refcount(p) for p in pages}
            assert len(refs) == 1, (
                f"adapter {aid} pages carry uneven refcounts {refs}")
            assert not (seen & set(pages)), (
                f"adapter {aid} shares pages with another adapter")
            seen.update(pages)
        assert len(seen) == self.alloc.in_use, (
            f"pool pages leaked outside residency: {self.alloc.in_use} in "
            f"use, {len(seen)} owned by resident adapters")


def make_adapter_store(model: Any, rank: int, num_pages: int,
                       page_elems: int = 2048,
                       registry: Any = None) -> AdapterStore:
    """Convenience: an :class:`AdapterStore` laid out for a serving
    wrapper's module (the object the engine's ``adapter_store=`` knob
    takes)."""
    return AdapterStore(AdapterLayout.for_model(model, rank, page_elems),
                        num_pages, registry=registry)
