"""Stage partitioning for pipeline parallelism.

TPU-native counterpart of the reference's FX-based partitioner
(``pipeline/partition.py``: ``partition_traced_model`` ``:17-42``,
``analyze_pipeline_module`` ``:75-222``, shared-weight analysis ``:225-250``).
The reference traces the model with torch.fx, marks cut nodes, and splits the
graph; on TPU the model is a *stack of identical transformer blocks* whose
parameters carry a leading layer axis, so a "partition" is just an assignment
of layer indices to stages — jaxprs are already functional and stage IO is
the homogeneous hidden-state tensor.

Shared weights (the reference's embedding/lm-head tying machinery,
``partition.py:225-250`` + dedicated grad process groups,
``parallel_state.py:347-379``) need no analysis here: non-stage parameters
(embedding, head, final norm) are replicated along the ``pp`` mesh axis, so a
weight referenced by several stages receives its summed gradient from the
shard_map transpose automatically.
"""

from __future__ import annotations

from typing import List, Sequence, Tuple


def partition_uniform(num_layers: int, num_stages: int) -> List[Tuple[int, int]]:
    """Balanced contiguous ``[start, end)`` layer spans, one per stage.

    When ``num_layers`` is not divisible, earlier stages receive the extra
    layers — they also hold more in-flight microbatches under 1F1B, but the
    imbalance is at most one layer (matching the reference's convention of
    user-chosen ``pipeline_cuts``)."""
    if num_stages < 1:
        raise ValueError("num_stages must be >= 1")
    if num_layers < num_stages:
        raise ValueError(f"cannot split {num_layers} layers into {num_stages} stages")
    base, extra = divmod(num_layers, num_stages)
    spans = []
    start = 0
    for s in range(num_stages):
        size = base + (1 if s < extra else 0)
        spans.append((start, start + size))
        start += size
    return spans


def spans_from_cuts(cuts: Sequence[int], num_layers: int) -> List[Tuple[int, int]]:
    """Spans from explicit cut points (the reference's ``pipeline_cuts``:
    layer indices that begin a new stage)."""
    bounds = [0, *cuts, num_layers]
    if bounds != sorted(bounds) or len(set(bounds)) != len(bounds):
        raise ValueError(f"cuts {cuts} must be strictly increasing within (0, {num_layers})")
    return [(bounds[i], bounds[i + 1]) for i in range(len(bounds) - 1)]


def layers_per_stage(num_layers: int, num_stages: int) -> int:
    """Uniform layer count per stage; raises unless evenly divisible (the
    stacked-parameter engine requires homogeneous stages)."""
    if num_layers % num_stages != 0:
        raise ValueError(
            f"num_layers={num_layers} must be divisible by num_stages={num_stages} "
            "for the stacked pipeline engine; pad the model or choose another pp size"
        )
    return num_layers // num_stages
