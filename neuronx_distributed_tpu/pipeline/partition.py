"""Stage partitioning for pipeline parallelism.

TPU-native counterpart of the reference's FX-based partitioner
(``pipeline/partition.py``: ``partition_traced_model`` ``:17-42``,
``analyze_pipeline_module`` ``:75-222``, shared-weight analysis ``:225-250``).
The reference traces the model with torch.fx, marks cut nodes, and splits the
graph; on TPU the model is a *stack of identical transformer blocks* whose
parameters carry a leading layer axis, so a "partition" is just an assignment
of layer indices to stages — jaxprs are already functional and stage IO is
the homogeneous hidden-state tensor.

Shared weights (the reference's embedding/lm-head tying machinery,
``partition.py:225-250`` + dedicated grad process groups,
``parallel_state.py:347-379``) need no analysis here: non-stage parameters
(embedding, head, final norm) are replicated along the ``pp`` mesh axis, so a
weight referenced by several stages receives its summed gradient from the
shard_map transpose automatically.
"""

from __future__ import annotations

from typing import List, Sequence, Tuple


def partition_uniform(num_layers: int, num_stages: int) -> List[Tuple[int, int]]:
    """Balanced contiguous ``[start, end)`` layer spans, one per stage.

    When ``num_layers`` is not divisible, earlier stages receive the extra
    layers — they also hold more in-flight microbatches under 1F1B, but the
    imbalance is at most one layer (matching the reference's convention of
    user-chosen ``pipeline_cuts``)."""
    if num_stages < 1:
        raise ValueError("num_stages must be >= 1")
    if num_layers < num_stages:
        raise ValueError(f"cannot split {num_layers} layers into {num_stages} stages")
    base, extra = divmod(num_layers, num_stages)
    spans = []
    start = 0
    for s in range(num_stages):
        size = base + (1 if s < extra else 0)
        spans.append((start, start + size))
        start += size
    return spans


def spans_from_cuts(cuts: Sequence[int], num_layers: int) -> List[Tuple[int, int]]:
    """Spans from explicit cut points (the reference's ``pipeline_cuts``:
    layer indices that begin a new stage)."""
    bounds = [0, *cuts, num_layers]
    if bounds != sorted(bounds) or len(set(bounds)) != len(bounds):
        raise ValueError(f"cuts {cuts} must be strictly increasing within (0, {num_layers})")
    return [(bounds[i], bounds[i + 1]) for i in range(len(bounds) - 1)]


def layers_per_stage(num_layers: int, num_stages: int) -> int:
    """Uniform layer count per stage; raises unless evenly divisible (the
    stacked-parameter engine requires homogeneous stages).  Non-divisible
    models are padded first — see :func:`padded_layer_layout`."""
    if num_layers % num_stages != 0:
        raise ValueError(
            f"num_layers={num_layers} must be divisible by num_stages={num_stages} "
            "for the stacked pipeline engine; pad the model or choose another pp size"
        )
    return num_layers // num_stages


def layout_from_spans(
    spans: Sequence[Tuple[int, int]], num_stages: int
) -> Tuple[int, List[int], List[int]]:
    """Padded stack layout realizing an arbitrary contiguous stage partition.

    The engine's "partition" is a sharding of a homogeneous ``[L', ...]``
    layer stack over ``pp``; ``L' = max-span * P`` with padded rows holding
    zero parameters and an ``active=0`` flag: the engine computes them
    uniformly (SPMD) but selects the identity, so numerics equal the
    unpadded model exactly and the ``where`` transpose zeroes their
    gradients.  Real layers fill each stage's leading rows.

    Returns ``(padded_len, row_of_layer, mask)``: ``row_of_layer[i]`` is the
    stack row of real layer ``i`` (execution order preserved), ``mask[r]``
    is 1 for real rows, 0 for padding; ``mask is None`` never happens here —
    callers drop the mask themselves when every span is full.
    """
    if len(spans) != num_stages:
        raise ValueError(f"{len(spans)} spans for {num_stages} stages")
    per = max(hi - lo for lo, hi in spans)
    padded = per * num_stages
    row_of_layer: List[int] = []
    mask = [0] * padded
    for s, (lo, hi) in enumerate(spans):
        for j in range(hi - lo):
            row = s * per + j
            row_of_layer.append(row)
            mask[row] = 1
    return padded, row_of_layer, mask


def interleaved_layout_from_spans(
    spans: Sequence[Tuple[int, int]], num_stages: int, num_chunks: int
) -> Tuple[int, List[int], List[int]]:
    """Padded stack layout for the interleaved (virtual-stage) assignment
    with arbitrary contiguous virtual-stage spans — what lets
    ``pipeline_cuts`` compose with ``virtual_stages`` (VERDICT r4 #3).

    ``spans`` has one ``[lo, hi)`` entry per *virtual* stage in execution
    order; virtual stage ``s = v*P + r`` (Megatron assignment) lives on rank
    ``r`` as its chunk ``v``.  Every chunk is padded to the widest span
    (``per``), so each rank's local stack is a uniform ``V*per`` rows —
    chunk ``v`` at local rows ``[v*per, (v+1)*per)`` — and the engine's
    dynamic chunk slicing stays shape-uniform; the mask marks real rows
    exactly as :func:`layout_from_spans` does for the contiguous layout.

    Returns ``(padded_len, row_of_layer, mask)`` with
    ``padded_len = P*V*per``; for uniform divisible spans the mask is all
    ones and the rows reproduce the classic interleaved assignment."""
    P, V = num_stages, num_chunks
    if len(spans) != P * V:
        raise ValueError(
            f"{len(spans)} spans for {P}*{V} virtual stages")
    per = max(hi - lo for lo, hi in spans)
    padded = per * P * V
    row_of_layer: List[int] = []
    mask = [0] * padded
    for s, (lo, hi) in enumerate(spans):
        v, r = divmod(s, P)
        for j in range(hi - lo):
            row = r * (V * per) + v * per + j
            row_of_layer.append(row)
            mask[row] = 1
    return padded, row_of_layer, mask


def padded_layer_layout(num_layers: int, num_stages: int) -> Tuple[int, List[int], List[int]]:
    """:func:`layout_from_spans` over the balanced :func:`partition_uniform`
    spans — the default layout for a non-divisible layer count (earlier
    stages take the extra layers, the reference's ``pipeline_cuts``
    convention, reference ``pipeline/partition.py:17-42``)."""
    return layout_from_spans(partition_uniform(num_layers, num_stages), num_stages)
