"""Pipeline-parallel task schedules (pure logic, backend-agnostic).

TPU-native counterpart of the reference's declarative schedules
(``pipeline/scheduler.py``: task taxonomy ``:4-49``, ``PipeSchedule`` ABC
``:52-125``, fwd-only ``InferenceSchedule`` ``:128-138``, 1F1B
``TrainSchedule`` ``:141-273``).  The reference drives an eager per-task
executor with these; here the production engine
(:mod:`neuronx_distributed_tpu.pipeline.engine`) compiles the whole schedule
into one jitted ``lax.scan``, so this module serves three purposes:

- it documents and *verifies* the schedule arithmetic (unit tests assert
  per-stage task sequences, mirroring the reference's scheduler tests);
- it computes the bubble / peak-activation analytics used to pick
  ``num_microbatches``;
- it remains available for a host-driven multi-dispatch executor.

The 1F1B shape: stage ``s`` of ``P`` runs ``min(M, P-1-s)`` warmup forwards,
then alternates one-forward-one-backward in the steady state, then drains the
remaining backwards.  Every stage executes exactly ``M`` forwards and ``M``
backwards; earlier stages hold at most ``P-s`` in-flight microbatches.
"""

from __future__ import annotations

import dataclasses
from typing import Iterator, List, Sequence


@dataclasses.dataclass(frozen=True)
class Task:
    """One schedulable unit; ``microbatch`` indexes the microbatch it acts on."""

    microbatch: int


class ForwardStep(Task):
    pass


class BackwardStep(Task):
    pass


class RecvForward(Task):
    """Receive the previous stage's activation for ``microbatch``."""


class SendForward(Task):
    """Send this stage's activation for ``microbatch`` to the next stage."""


class RecvBackward(Task):
    """Receive the next stage's activation-gradient for ``microbatch``."""


class SendBackward(Task):
    """Send the activation-gradient for ``microbatch`` to the previous stage."""


@dataclasses.dataclass(frozen=True)
class ReduceGrads:
    """End-of-batch gradient reduction (reference ``ReduceGradsTask``)."""


class PipeSchedule:
    """Base schedule: yields the ordered task list for one stage
    (reference ``PipeSchedule``, ``pipeline/scheduler.py:52-125``)."""

    def __init__(self, num_microbatches: int, num_stages: int, stage_id: int):
        if not 0 <= stage_id < num_stages:
            raise ValueError(f"stage_id {stage_id} out of range for {num_stages} stages")
        if num_microbatches < 1:
            raise ValueError("num_microbatches must be >= 1")
        self.num_microbatches = num_microbatches
        self.num_stages = num_stages
        self.stage_id = stage_id

    @property
    def is_first_stage(self) -> bool:
        return self.stage_id == 0

    @property
    def is_last_stage(self) -> bool:
        return self.stage_id == self.num_stages - 1

    def steps(self) -> Iterator[List[object]]:
        """Yield groups of tasks; tasks within a group may run concurrently."""
        raise NotImplementedError

    def tasks(self) -> List[object]:
        """Flat ordered task list."""
        return [t for group in self.steps() for t in group]

    def num_in_flight(self) -> int:
        """Peak number of microbatches whose activations this stage holds."""
        raise NotImplementedError


class InferenceSchedule(PipeSchedule):
    """Forward-only fill-drain (reference ``InferenceSchedule``,
    ``pipeline/scheduler.py:128-138``)."""

    def steps(self) -> Iterator[List[object]]:
        for mb in range(self.num_microbatches):
            group: List[object] = []
            if not self.is_first_stage:
                group.append(RecvForward(mb))
            group.append(ForwardStep(mb))
            if not self.is_last_stage:
                group.append(SendForward(mb))
            yield group

    def num_in_flight(self) -> int:
        return 1


class TrainSchedule(PipeSchedule):
    """1F1B (reference ``TrainSchedule``, ``pipeline/scheduler.py:141-273``).

    Warmup forwards fill the pipeline, the steady state interleaves one
    forward with one backward (receiving before sending so neighbor pairs
    never deadlock — the reference's recv-before-send rule,
    ``scheduler.py:174-180``), and the cooldown drains the backwards."""

    @property
    def num_warmup(self) -> int:
        return min(self.num_microbatches, self.num_stages - 1 - self.stage_id)

    def steps(self) -> Iterator[List[object]]:
        M, warmup = self.num_microbatches, self.num_warmup
        steady = M - warmup

        for mb in range(warmup):
            group: List[object] = []
            if not self.is_first_stage:
                group.append(RecvForward(mb))
            group.append(ForwardStep(mb))
            if not self.is_last_stage:
                group.append(SendForward(mb))
            yield group

        for i in range(steady):
            f_mb, b_mb = warmup + i, i
            group = []
            if not self.is_first_stage:
                group.append(RecvForward(f_mb))
            group.append(ForwardStep(f_mb))
            # recv the backward before sending the forward: the conjugate
            # neighbor (later stage) is sending this grad before it posts its
            # own forward recv, so the pair always matches up.
            if not self.is_last_stage:
                group.append(RecvBackward(b_mb))
                group.append(SendForward(f_mb))
            group.append(BackwardStep(b_mb))
            if not self.is_first_stage:
                group.append(SendBackward(b_mb))
            yield group

        for mb in range(steady, M):
            group = []
            if not self.is_last_stage:
                group.append(RecvBackward(mb))
            group.append(BackwardStep(mb))
            if not self.is_first_stage:
                group.append(SendBackward(mb))
            yield group

        yield [ReduceGrads()]

    def num_in_flight(self) -> int:
        return min(self.num_microbatches, self.num_stages - self.stage_id)


def bubble_fraction(num_microbatches: int, num_stages: int) -> float:
    """Pipeline bubble fraction (P-1)/(M+P-1) — identical for GPipe-style
    fill-drain and 1F1B; 1F1B only lowers peak activation memory."""
    return (num_stages - 1) / (num_microbatches + num_stages - 1)
