"""Pipeline-parallel task schedules (pure logic, backend-agnostic).

TPU-native counterpart of the reference's declarative schedules
(``pipeline/scheduler.py``: task taxonomy ``:4-49``, ``PipeSchedule`` ABC
``:52-125``, fwd-only ``InferenceSchedule`` ``:128-138``, 1F1B
``TrainSchedule`` ``:141-273``).  The reference drives an eager per-task
executor with these; here the production engine
(:mod:`neuronx_distributed_tpu.pipeline.engine`) compiles the whole schedule
into one jitted ``lax.scan``, so this module serves three purposes:

- it documents and *verifies* the schedule arithmetic (unit tests assert
  per-stage task sequences, mirroring the reference's scheduler tests);
- it computes the bubble / peak-activation analytics used to pick
  ``num_microbatches``;
- it remains available for a host-driven multi-dispatch executor.

The 1F1B shape: stage ``s`` of ``P`` runs ``min(M, P-1-s)`` warmup forwards,
then alternates one-forward-one-backward in the steady state, then drains the
remaining backwards.  Every stage executes exactly ``M`` forwards and ``M``
backwards; earlier stages hold at most ``P-s`` in-flight microbatches.
"""

from __future__ import annotations

import dataclasses
from typing import Iterator, List, Optional, Sequence, Tuple


@dataclasses.dataclass(frozen=True)
class Task:
    """One schedulable unit; ``microbatch`` indexes the microbatch it acts on."""

    microbatch: int


class ForwardStep(Task):
    pass


class BackwardStep(Task):
    pass


class RecvForward(Task):
    """Receive the previous stage's activation for ``microbatch``."""


class SendForward(Task):
    """Send this stage's activation for ``microbatch`` to the next stage."""


class RecvBackward(Task):
    """Receive the next stage's activation-gradient for ``microbatch``."""


class SendBackward(Task):
    """Send the activation-gradient for ``microbatch`` to the previous stage."""


@dataclasses.dataclass(frozen=True)
class ReduceGrads:
    """End-of-batch gradient reduction (reference ``ReduceGradsTask``)."""


class PipeSchedule:
    """Base schedule: yields the ordered task list for one stage
    (reference ``PipeSchedule``, ``pipeline/scheduler.py:52-125``)."""

    def __init__(self, num_microbatches: int, num_stages: int, stage_id: int):
        if not 0 <= stage_id < num_stages:
            raise ValueError(f"stage_id {stage_id} out of range for {num_stages} stages")
        if num_microbatches < 1:
            raise ValueError("num_microbatches must be >= 1")
        self.num_microbatches = num_microbatches
        self.num_stages = num_stages
        self.stage_id = stage_id

    @property
    def is_first_stage(self) -> bool:
        return self.stage_id == 0

    @property
    def is_last_stage(self) -> bool:
        return self.stage_id == self.num_stages - 1

    def steps(self) -> Iterator[List[object]]:
        """Yield groups of tasks; tasks within a group may run concurrently."""
        raise NotImplementedError

    def tasks(self) -> List[object]:
        """Flat ordered task list."""
        return [t for group in self.steps() for t in group]

    def num_in_flight(self) -> int:
        """Peak number of microbatches whose activations this stage holds."""
        raise NotImplementedError


class InferenceSchedule(PipeSchedule):
    """Forward-only fill-drain (reference ``InferenceSchedule``,
    ``pipeline/scheduler.py:128-138``)."""

    def steps(self) -> Iterator[List[object]]:
        for mb in range(self.num_microbatches):
            group: List[object] = []
            if not self.is_first_stage:
                group.append(RecvForward(mb))
            group.append(ForwardStep(mb))
            if not self.is_last_stage:
                group.append(SendForward(mb))
            yield group

    def num_in_flight(self) -> int:
        return 1


class TrainSchedule(PipeSchedule):
    """1F1B (reference ``TrainSchedule``, ``pipeline/scheduler.py:141-273``).

    Warmup forwards fill the pipeline, the steady state interleaves one
    forward with one backward (receiving before sending so neighbor pairs
    never deadlock — the reference's recv-before-send rule,
    ``scheduler.py:174-180``), and the cooldown drains the backwards."""

    @property
    def num_warmup(self) -> int:
        return min(self.num_microbatches, self.num_stages - 1 - self.stage_id)

    def steps(self) -> Iterator[List[object]]:
        M, warmup = self.num_microbatches, self.num_warmup
        steady = M - warmup

        for mb in range(warmup):
            group: List[object] = []
            if not self.is_first_stage:
                group.append(RecvForward(mb))
            group.append(ForwardStep(mb))
            if not self.is_last_stage:
                group.append(SendForward(mb))
            yield group

        for i in range(steady):
            f_mb, b_mb = warmup + i, i
            group = []
            if not self.is_first_stage:
                group.append(RecvForward(f_mb))
            group.append(ForwardStep(f_mb))
            # recv the backward before sending the forward: the conjugate
            # neighbor (later stage) is sending this grad before it posts its
            # own forward recv, so the pair always matches up.
            if not self.is_last_stage:
                group.append(RecvBackward(b_mb))
                group.append(SendForward(f_mb))
            group.append(BackwardStep(b_mb))
            if not self.is_first_stage:
                group.append(SendBackward(b_mb))
            yield group

        for mb in range(steady, M):
            group = []
            if not self.is_last_stage:
                group.append(RecvBackward(mb))
            group.append(BackwardStep(mb))
            if not self.is_first_stage:
                group.append(SendBackward(mb))
            yield group

        yield [ReduceGrads()]

    def num_in_flight(self) -> int:
        return min(self.num_microbatches, self.num_stages - self.stage_id)


def bubble_fraction(
    num_microbatches: int, num_stages: int, schedule: str = "eager",
    num_chunks: int = 1,
) -> float:
    """Fraction of pipeline compute capacity wasted on bubbles.

    ``schedule="eager"`` — the classic fill-drain / 1F1B figure
    ``(P-1)/(M+P-1)``: what a per-task executor (the reference's
    ``NxDPPModel``) achieves; identical for GPipe and 1F1B, which differ
    only in peak activation memory.

    ``schedule="sync_1f1b"`` — the production single-jit engine's timetable
    (:func:`build_sync_slot_tables`): ``T = M + 2(P-1)`` ticks, each costing
    one full fwd+bwd on every rank, of which ``M`` carry useful pairs —
    overhead ``2(P-1)/(M+2(P-1))``, roughly TWICE the eager bubble at equal
    ``M`` (43% vs 27% at P=4/M=8; 4.3% vs 2.2% at P=4/M=128).  This is the
    price of SPMD uniformity (no rank-divergent control flow around
    collective-bearing compute), and it amortizes with large ``M`` exactly
    like the eager bubble.  Note the asymmetric timetable
    (:func:`build_slot_tables`) is NOT an improvement under the uniformity
    constraint: realized as masked uniform ticks its ``~2M + 2(P-1)`` slots
    would each still pay a full fwd+bwd, costing strictly more than the
    sync form — a true eager 1F1B needs per-rank divergent dispatch, which
    this engine rules out by design (see ``engine.py``).  On top of the
    bubble, the sync engine pays the embedding+head on every tick
    (:func:`sync_1f1b_head_overhead`).
    """
    M, P = num_microbatches, num_stages
    if schedule == "eager":
        return (P - 1) / (M + P - 1)
    if schedule == "sync_1f1b":
        return 2 * (P - 1) / (M + 2 * (P - 1))
    if schedule == "sync_interleaved":
        # ``sync_interleaved``: V chunks per rank, chunk-granular ticks, and
        # the engine's PHASE-SPLIT scans (fwd-only warmup / mixed middle /
        # bwd-only drain — tick-dependent but rank-uniform control flow is
        # SPMD-legal, so warm/drain ticks stop paying the garbage half).
        # Cost model: fwd-only tick = 1 unit, bwd-only = 2 (bwd ≈ 2x fwd
        # FLOPs), mixed = 3; useful work = 3 units per microbatch-chunk.
        # The fill/drain now costs chunk-ticks, which is how interleaving
        # divides the bubble (Megatron interleaved 1F1B; the reference has
        # no interleaving at all, SURVEY §2.10).
        tables = build_interleaved_sync_tables(M, P, num_chunks)
        T = tables.num_slots
        any_b = [any(tables.bwd_mb[r][t] >= 0 for r in range(P)) for t in range(T)]
        any_f = [any(tables.fwd_mb[r][t] >= 0 for r in range(P)) for t in range(T)]
        warm = any_b.index(True) if any(any_b) else T
        drain_start = T - list(reversed(any_f)).index(True) if any(any_f) else 0
        total = warm * 1 + (drain_start - warm) * 3 + (T - drain_start) * 2
        useful = 3 * M * num_chunks
        return (total - useful) / total
    raise ValueError(
        f"unknown schedule {schedule!r} (eager | sync_1f1b | sync_interleaved)"
    )


def export_schedule_metrics(
    registry,
    num_microbatches: int,
    num_stages: int,
    schedule: str = "sync_1f1b",
    num_chunks: int = 1,
    prefix: str = "pipeline",
) -> dict:
    """Publish the schedule analytics this module computes as observability
    gauges (``obs.MetricRegistry``), so a run's pipeline efficiency is part
    of its persisted telemetry instead of a hand-run calculation.

    Sets ``{prefix}/bubble_fraction``, the shape parameters, and — for the
    engine timetables — tick count and stash sizes (the peak-activation
    memory knob).  Returns the values set, keyed by gauge name."""
    vals = {
        f"{prefix}/num_microbatches": float(num_microbatches),
        f"{prefix}/num_stages": float(num_stages),
        f"{prefix}/num_chunks": float(num_chunks),
        f"{prefix}/bubble_fraction": bubble_fraction(
            num_microbatches, num_stages, schedule, num_chunks),
    }
    if schedule == "sync_1f1b":
        tables = build_sync_slot_tables(num_microbatches, num_stages)
        vals[f"{prefix}/num_slots"] = float(tables.num_slots)
        vals[f"{prefix}/fwd_stash_size"] = float(tables.fwd_stash_size)
        vals[f"{prefix}/bwd_stash_size"] = float(tables.bwd_stash_size)
    elif schedule == "sync_interleaved":
        tables = build_interleaved_sync_tables(
            num_microbatches, num_stages, num_chunks)
        vals[f"{prefix}/num_slots"] = float(tables.num_slots)
        vals[f"{prefix}/fwd_stash_size"] = float(tables.stash_size)
        vals[f"{prefix}/bwd_stash_size"] = float(tables.gstash_size)
    for name, v in vals.items():
        registry.gauge(name).set(v)
    return vals


def sync_1f1b_head_overhead(
    num_layers: int,
    num_stages: int,
    hidden: int,
    vocab: int,
    intermediate: Optional[int] = None,
) -> float:
    """Per-tick compute imbalance from the LAST stage owning the LM-head.

    The engines run embed/head under ``lax.cond`` on the owning pp rank
    (pp-uniform predicate — every auto-axis collective channel inside takes
    one branch), so the head is no longer paid on every rank; what remains
    is that the last stage's tick costs ``layers_per_stage`` blocks + one
    ``hidden x vocab`` matmul while the others cost blocks alone, and the
    synchronous tick waits for the slowest rank.  This function returns that
    critical-path excess as a fraction of a balanced stage.  Per-token fwd
    matmul FLOPs (MHA): qkv ``6h²`` + o-proj ``2h²`` + mlp ``6hi`` → block =
    ``8h² + 6hi``; head = ``2hV`` (same ratio holds fwd+bwd; attention-core
    FLOPs excluded, so this slightly over-states).  ≈8% for 7B/PP4, ≈1% for
    70B/PP4 — and removable by giving the last stage fewer layers via
    ``pipeline_cuts`` (one layer ≈ head when ``2hV ≈ 8h²+6hi``)."""
    i = intermediate if intermediate is not None else 4 * hidden
    lps = num_layers / num_stages
    block = 8 * hidden * hidden + 6 * hidden * i
    head = 2 * hidden * vocab
    return head / (lps * block)


@dataclasses.dataclass(frozen=True)
class SlotTables:
    """Global-clock realization of a 1F1B schedule.  The single-jit engine
    (:func:`..engine.make_1f1b_loss_and_grad_fn`) consumes the synchronous
    variant (:func:`build_sync_slot_tables`); the asynchronous
    :func:`build_slot_tables` (one op per stage per slot, derived from
    :class:`TrainSchedule`) is the verification oracle the tests check both
    against, and the timetable a host-driven multi-dispatch executor would
    follow.

    Each stage performs at most one compute op per slot. ``fwd_mb[s][t]`` /
    ``bwd_mb[s][t]`` give the microbatch whose forward/backward stage ``s``
    runs at slot ``t`` (-1 = none).  ``fwd_stash_size`` / ``bwd_stash_size``
    bound the circular activation / incoming-grad stashes indexed by
    ``microbatch % size`` — the engine's peak-activation memory is
    ``fwd_stash_size`` microbatch activations per stage (≤ P, vs M for
    fill-drain autodiff; the reference's in-flight bound,
    ``pipeline/scheduler.py:141-273``)."""

    num_microbatches: int
    num_stages: int
    num_slots: int
    fwd_mb: Tuple[Tuple[int, ...], ...]  # [P][T]
    bwd_mb: Tuple[Tuple[int, ...], ...]  # [P][T]
    fwd_stash_size: int
    bwd_stash_size: int


def build_slot_tables(num_microbatches: int, num_stages: int) -> SlotTables:
    """Assign every stage's :class:`TrainSchedule` op sequence to global
    slots, greedily and dependency-honoring:

    - ``fwd(s, m)`` needs ``fwd(s-1, m)`` completed in an earlier slot (the
      activation arrives via the engine's end-of-slot ppermute);
    - ``bwd(s, m)`` needs ``fwd(s, m)`` done and, for ``s < P-1``,
      ``bwd(s+1, m)`` completed in an earlier slot.

    Every stage consumes its ops in TrainSchedule order (warmup forwards →
    1F1B steady state → backward drain), so the result *is* the 1F1B
    timetable with bubbles made explicit."""
    M, P = num_microbatches, num_stages
    seqs: List[List[Task]] = []
    for s in range(P):
        seqs.append([
            t for t in TrainSchedule(M, P, s).tasks()
            if isinstance(t, (ForwardStep, BackwardStep))
        ])

    fwd_done = [[-1] * M for _ in range(P)]
    bwd_done = [[-1] * M for _ in range(P)]
    idx = [0] * P
    fwd_rows: List[List[int]] = [[] for _ in range(P)]
    bwd_rows: List[List[int]] = [[] for _ in range(P)]

    t = 0
    while any(idx[s] < len(seqs[s]) for s in range(P)):
        for s in range(P):
            f_op, b_op = -1, -1
            if idx[s] < len(seqs[s]):
                op = seqs[s][idx[s]]
                m = op.microbatch
                if isinstance(op, ForwardStep):
                    if s == 0 or 0 <= fwd_done[s - 1][m] < t:
                        f_op = m
                        fwd_done[s][m] = t
                        idx[s] += 1
                else:
                    ready = 0 <= fwd_done[s][m] < t or (s == P - 1 and fwd_done[s][m] >= 0)
                    if s < P - 1:
                        ready = ready and 0 <= bwd_done[s + 1][m] < t
                    if ready:
                        b_op = m
                        bwd_done[s][m] = t
                        idx[s] += 1
            fwd_rows[s].append(f_op)
            bwd_rows[s].append(b_op)
        t += 1
        if t > 4 * (M + P) + 8:  # pragma: no cover - schedule bug guard
            raise RuntimeError(f"1F1B slot assignment did not converge (M={M}, P={P})")

    T = t

    def _min_stash(intervals_by_index) -> int:
        """Smallest K such that mb%K circular indexing never collides two
        live intervals."""
        for K in range(1, P + 2):
            ok = True
            for s_ints in intervals_by_index:
                by_slot: dict = {}
                for m, (lo, hi) in s_ints:
                    by_slot.setdefault(m % K, []).append((lo, hi))
                for spans in by_slot.values():
                    spans.sort()
                    for a, b in zip(spans, spans[1:]):
                        if b[0] <= a[1]:
                            ok = False
            if ok:
                return K
        raise RuntimeError("no valid stash size <= P+1")  # pragma: no cover

    # fwd stash entry for (s, m): written at end of the slot the activation
    # is produced upstream (or during the fwd slot itself at stage 0), read
    # at the bwd slot.
    fwd_ints = []
    for s in range(P):
        ints = []
        for m in range(M):
            lo = fwd_done[s][m] if s == 0 else fwd_done[s - 1][m] + 1
            ints.append((m, (lo, bwd_done[s][m])))
        fwd_ints.append(ints)
    # bwd (incoming-grad) stash entry for (s, m): written at end of the slot
    # bwd(s+1, m) ran, read at bwd(s, m).  Last stage seeds its own grads.
    bwd_ints = []
    for s in range(P - 1):
        ints = []
        for m in range(M):
            ints.append((m, (bwd_done[s + 1][m] + 1, bwd_done[s][m])))
        bwd_ints.append(ints)

    return SlotTables(
        num_microbatches=M,
        num_stages=P,
        num_slots=T,
        fwd_mb=tuple(tuple(r) for r in fwd_rows),
        bwd_mb=tuple(tuple(r) for r in bwd_rows),
        fwd_stash_size=_min_stash(fwd_ints),
        bwd_stash_size=_min_stash(bwd_ints) if bwd_ints else 1,
    )


@dataclasses.dataclass(frozen=True)
class InterleavedSlotTables:
    """Tick tables for the interleaved (virtual-stage) synchronous 1F1B.

    ``V`` model chunks per pp rank; virtual stage ``s = v * P + r`` lives on
    rank ``r`` as its chunk ``v`` — the Megatron interleaved assignment
    (absent from the reference, SURVEY §2.10 "interleaved: Absent"), chosen
    because consecutive virtual stages sit on consecutive ranks, so ONE ring
    ppermute per tick still moves every edge, including the rank ``P-1 →
    0`` chunk wrap.

    Every tick each rank runs at most one chunk-forward and one
    chunk-backward (1/V of a full stage each), so the fill/drain overhead
    costs chunk-ticks, not stage-ticks: measured ticks ``T ≈ MV + O(P·V
    drain)`` against ``MV`` useful — at P=4/M=8: 43% (V=1) → ~30% (V=2) →
    ~21% (V=4) bubble, approaching the eager engine's 27%@V=1 figure from
    a fully-SPMD program (see ``bubble_fraction(..., "sync_interleaved")``).

    All index tables are ``[P][T]`` (-1 = none).  Stash slots are allocated
    offline by live-interval graph coloring (`slots` = per-rank maximum),
    so the engine does no modular-index arithmetic: it reads the slot
    number for the tick from the table."""

    num_microbatches: int
    num_stages: int       # pp ranks P
    num_chunks: int       # V
    num_slots: int        # ticks T
    # compute tables
    fwd_mb: Tuple[Tuple[int, ...], ...]
    fwd_chunk: Tuple[Tuple[int, ...], ...]
    bwd_mb: Tuple[Tuple[int, ...], ...]
    bwd_chunk: Tuple[Tuple[int, ...], ...]
    # activation-stash slot tables
    fwd_slot: Tuple[Tuple[int, ...], ...]     # slot holding this fwd's input
    bwd_slot: Tuple[Tuple[int, ...], ...]     # slot holding this bwd's stashed input
    in_fwd_slot: Tuple[Tuple[int, ...], ...]  # slot to store the arriving activation
    stash_size: int
    # incoming-grad stash
    gin_slot: Tuple[Tuple[int, ...], ...]     # slot holding this bwd's incoming grad
    in_bwd_slot: Tuple[Tuple[int, ...], ...]  # slot to store the arriving grad
    gstash_size: int


def build_interleaved_sync_tables(
    num_microbatches: int, num_stages: int, num_chunks: int
) -> InterleavedSlotTables:
    """Greedy dependency-honoring tick assignment for interleaved sync-1F1B.

    Issue order per rank follows Megatron's interleaved 1F1B (chunk-major
    groups of P microbatches: ``for each group of P mbs: for each chunk:
    the P mbs``; backwards mirrored chunk-descending), with each op placed
    at the earliest tick satisfying:

    - ``fwd(s, m)`` needs ``fwd(s-1, m)`` in an *earlier* tick (activation
      arrives via the end-of-tick ppermute);
    - ``bwd(s, m)`` needs ``bwd(s+1, m)`` in an earlier tick, and
      ``fwd(s, m)`` in an earlier-or-equal tick (the backward recomputes
      the stage forward from the stashed input; at the last virtual stage
      fwd and bwd of a microbatch share the tick, as in the V=1 engine);
    - at most one fwd and one bwd per rank per tick;
    - per-rank ops issue in order (pointer semantics, like the engine's
      sequential consumption of its tick table).

    Activation-stash live intervals ``[arrival(or fwd tick for s=0), bwd
    tick]`` and grad intervals ``[arrival, bwd tick]`` are then colored
    into the minimum slot count per rank (max over ranks = stash shape).

    ``M`` need NOT be a multiple of ``P`` (VERDICT r4 #3): the issue order
    is built over ``M`` padded up to the next multiple (Megatron's group
    structure), ghost microbatches are then erased from every table
    (``-1`` = none — the engine's existing masking skips them uniformly),
    ghost-only ticks are compacted away, and slot coloring sees only real
    microbatches.  A ragged tail costs a slightly larger bubble than a
    divisible ``M``, never a wrong result."""
    M_real, P, V = num_microbatches, num_stages, num_chunks
    if V < 1:
        raise ValueError(f"num_chunks must be >= 1, got {V}")
    if M_real < 1:
        raise ValueError(f"num_microbatches must be >= 1, got {M_real}")
    M = -(-M_real // P) * P  # padded for the group-of-P issue order
    S = V * P

    def owner(s):
        return s % P

    def chunk(s):
        return s // P

    # per-rank issue orders
    fwd_order: List[List[Tuple[int, int]]] = [[] for _ in range(P)]  # (s, m)
    bwd_order: List[List[Tuple[int, int]]] = [[] for _ in range(P)]
    for g in range(M // P):
        for v in range(V):
            for j in range(P):
                m = g * P + j
                for r in range(P):
                    fwd_order[r].append((v * P + r, m))
        for v in reversed(range(V)):
            for j in range(P):
                m = g * P + j
                for r in range(P):
                    bwd_order[r].append((v * P + r, m))

    fwd_done = {}
    bwd_done = {}
    fi = [0] * P
    bi = [0] * P
    rows: dict = {k: [[] for _ in range(P)] for k in ("fm", "fc", "bm", "bc")}
    t = 0
    while any(fi[r] < len(fwd_order[r]) or bi[r] < len(bwd_order[r])
              for r in range(P)):
        placed_f = {}
        placed_b = {}
        for r in range(P):
            f_sm = b_sm = None
            if fi[r] < len(fwd_order[r]):
                s, m = fwd_order[r][fi[r]]
                if s == 0 or fwd_done.get((s - 1, m), t) < t:
                    f_sm = (s, m)
                    fi[r] += 1
            if bi[r] < len(bwd_order[r]):
                s, m = bwd_order[r][bi[r]]
                f_t = fwd_done.get((s, m))
                if f_sm == (s, m):  # same tick fwd (last virtual stage)
                    f_t = t
                ready = f_t is not None and f_t <= t
                if s < S - 1:
                    ready = ready and bwd_done.get((s + 1, m), t) < t
                if ready:
                    b_sm = (s, m)
                    bi[r] += 1
            placed_f[r] = f_sm
            placed_b[r] = b_sm
        for r in range(P):
            f_sm, b_sm = placed_f[r], placed_b[r]
            if f_sm is not None:
                fwd_done[f_sm] = t
            if b_sm is not None:
                bwd_done[b_sm] = t
            # ghost microbatches (m >= M_real, the divisibility padding)
            # keep their dependency bookkeeping but never reach the tables
            f_real = f_sm is not None and f_sm[1] < M_real
            b_real = b_sm is not None and b_sm[1] < M_real
            rows["fm"][r].append(f_sm[1] if f_real else -1)
            rows["fc"][r].append(chunk(f_sm[0]) if f_real else -1)
            rows["bm"][r].append(b_sm[1] if b_real else -1)
            rows["bc"][r].append(chunk(b_sm[0]) if b_real else -1)
        t += 1
        if t > 4 * (M * V + S) + 16:  # pragma: no cover - schedule bug guard
            raise RuntimeError(
                f"interleaved slot assignment did not converge (M={M}, P={P}, V={V})"
            )
    T = t

    # ---- offline stash slot allocation (interval coloring per rank) ----
    color = _color_intervals

    fwd_slot = [[-1] * T for _ in range(P)]
    bwd_slot = [[-1] * T for _ in range(P)]
    in_fwd_slot = [[-1] * T for _ in range(P)]
    gin_slot = [[-1] * T for _ in range(P)]
    in_bwd_slot = [[-1] * T for _ in range(P)]
    stash_size = 1
    gstash_size = 1
    for r in range(P):
        # activation intervals: input of (s, m) lives from its availability
        # (fwd tick for virtual stage 0; arrival tick otherwise) to its bwd.
        # Only REAL microbatches get slots (ghosts never store anything).
        acts = []
        for s in range(r, S, P):
            for m in range(M_real):
                start = fwd_done[(s, m)] if s == 0 else fwd_done[(s - 1, m)] + 1
                acts.append((start, bwd_done[(s, m)], (s, m)))
        assign, n = color(acts)
        stash_size = max(stash_size, n)
        grads = []
        for s in range(r, S, P):
            if s == S - 1:
                continue
            for m in range(M_real):
                grads.append(
                    (bwd_done[(s + 1, m)] + 1, bwd_done[(s, m)], (s, m)))
        gassign, gn = color(grads)
        gstash_size = max(gstash_size, gn)
        for t_ in range(T):
            fm, fc = rows["fm"][r][t_], rows["fc"][r][t_]
            if fm >= 0:
                fwd_slot[r][t_] = assign[(fc * P + r, fm)]
            bm, bc = rows["bm"][r][t_], rows["bc"][r][t_]
            if bm >= 0:
                s = bc * P + r
                bwd_slot[r][t_] = assign[(s, m_ := bm)]
                if s < S - 1:
                    gin_slot[r][t_] = gassign[(s, m_)]
        # arrival tables: what lands at the END of tick t_ on this rank
        prev_r = (r - 1) % P
        next_r = (r + 1) % P
        for t_ in range(T):
            pm, pc = rows["fm"][prev_r][t_], rows["fc"][prev_r][t_]
            if pm >= 0:
                s_sender = pc * P + prev_r
                if s_sender + 1 < S and owner(s_sender + 1) == r:
                    in_fwd_slot[r][t_] = assign[(s_sender + 1, pm)]
            nm, nc = rows["bm"][next_r][t_], rows["bc"][next_r][t_]
            if nm >= 0:
                s_sender = nc * P + next_r
                if s_sender - 1 >= 0 and owner(s_sender - 1) == r:
                    in_bwd_slot[r][t_] = gassign[(s_sender - 1, nm)]

    # compact ghost-only ticks: a tick where no rank computes also sends
    # nothing (arrivals are set only opposite a sender's compute entry), so
    # dropping it preserves every strict tick-order dependency
    keep = [t_ for t_ in range(T)
            if any(rows["fm"][r][t_] >= 0 or rows["bm"][r][t_] >= 0
                   for r in range(P))]
    sel = lambda rows_: tuple(  # noqa: E731
        tuple(rows_[r][t_] for t_ in keep) for r in range(P))
    return InterleavedSlotTables(
        num_microbatches=M_real,
        num_stages=P,
        num_chunks=V,
        num_slots=len(keep),
        fwd_mb=sel(rows["fm"]),
        fwd_chunk=sel(rows["fc"]),
        bwd_mb=sel(rows["bm"]),
        bwd_chunk=sel(rows["bc"]),
        fwd_slot=sel(fwd_slot),
        bwd_slot=sel(bwd_slot),
        in_fwd_slot=sel(in_fwd_slot),
        stash_size=stash_size,
        gin_slot=sel(gin_slot),
        in_bwd_slot=sel(in_bwd_slot),
        gstash_size=gstash_size,
    )


@dataclasses.dataclass(frozen=True)
class InterleavedFwdTables:
    """Forward-only interleaved timetable (fill-drain over virtual stages):
    drives the differentiable loss oracle and the inference path of the
    interleaved engine (``InferenceSchedule`` analogue)."""

    num_microbatches: int
    num_stages: int
    num_chunks: int
    num_slots: int
    fwd_mb: Tuple[Tuple[int, ...], ...]
    fwd_chunk: Tuple[Tuple[int, ...], ...]
    fwd_slot: Tuple[Tuple[int, ...], ...]
    in_fwd_slot: Tuple[Tuple[int, ...], ...]
    stash_size: int


def _color_intervals(intervals):
    """First-fit interval coloring: ``intervals`` of (start, end, key) →
    (assignment dict, slot count).  A slot is reusable the tick after its
    previous occupant's last read (strict ``<`` on starts)."""
    intervals = sorted(intervals)
    slot_free_at: List[int] = []
    assign = {}
    for lo, hi, key in intervals:
        for i, free in enumerate(slot_free_at):
            if free < lo:
                slot_free_at[i] = hi
                assign[key] = i
                break
        else:
            assign[key] = len(slot_free_at)
            slot_free_at.append(hi)
    return assign, len(slot_free_at)


def build_interleaved_fwd_tables(
    num_microbatches: int, num_stages: int, num_chunks: int
) -> InterleavedFwdTables:
    """Greedy earliest-tick assignment of the interleaved *forward* pass:
    per-rank Megatron chunk-major issue order, one fwd per rank per tick,
    activation available the tick after the producing tick (ppermute).
    ``M`` need not divide ``P`` — same ghost-padding/erase/compact scheme
    as :func:`build_interleaved_sync_tables`."""
    M_real, P, V = num_microbatches, num_stages, num_chunks
    if M_real < 1:
        raise ValueError(f"num_microbatches must be >= 1, got {M_real}")
    M = -(-M_real // P) * P
    S = V * P
    fwd_order: List[List[Tuple[int, int]]] = [[] for _ in range(P)]
    for g in range(M // P):
        for v in range(V):
            for j in range(P):
                m = g * P + j
                for r in range(P):
                    fwd_order[r].append((v * P + r, m))

    fwd_done = {}
    fi = [0] * P
    fm_rows: List[List[int]] = [[] for _ in range(P)]
    fc_rows: List[List[int]] = [[] for _ in range(P)]
    t = 0
    while any(fi[r] < len(fwd_order[r]) for r in range(P)):
        placed = {}
        for r in range(P):
            placed[r] = None
            if fi[r] < len(fwd_order[r]):
                s, m = fwd_order[r][fi[r]]
                if s == 0 or fwd_done.get((s - 1, m), t) < t:
                    placed[r] = (s, m)
                    fi[r] += 1
        for r in range(P):
            sm = placed[r]
            if sm is not None:
                fwd_done[sm] = t
            real = sm is not None and sm[1] < M_real
            fm_rows[r].append(sm[1] if real else -1)
            fc_rows[r].append(sm[0] // P if real else -1)
        t += 1
        if t > 4 * (M * V + S) + 16:  # pragma: no cover
            raise RuntimeError("interleaved fwd assignment did not converge")
    T = t

    fwd_slot = [[-1] * T for _ in range(P)]
    in_fwd_slot = [[-1] * T for _ in range(P)]
    stash_size = 1
    for r in range(P):
        acts = []
        for s in range(r, S, P):
            for m in range(M_real):
                start = fwd_done[(s, m)] if s == 0 else fwd_done[(s - 1, m)] + 1
                acts.append((start, fwd_done[(s, m)], (s, m)))
        assign, n = _color_intervals(acts)
        stash_size = max(stash_size, n)
        for t_ in range(T):
            fm, fc = fm_rows[r][t_], fc_rows[r][t_]
            if fm >= 0:
                fwd_slot[r][t_] = assign[(fc * P + r, fm)]
        prev_r = (r - 1) % P
        for t_ in range(T):
            pm, pc = fm_rows[prev_r][t_], fc_rows[prev_r][t_]
            if pm >= 0:
                s_sender = pc * P + prev_r
                if s_sender + 1 < S and (s_sender + 1) % P == r:
                    in_fwd_slot[r][t_] = assign[(s_sender + 1, pm)]

    keep = [t_ for t_ in range(T)
            if any(fm_rows[r][t_] >= 0 for r in range(P))]
    sel = lambda rows_: tuple(  # noqa: E731
        tuple(rows_[r][t_] for t_ in keep) for r in range(P))
    return InterleavedFwdTables(
        num_microbatches=M_real, num_stages=P, num_chunks=V,
        num_slots=len(keep),
        fwd_mb=sel(fm_rows), fwd_chunk=sel(fc_rows), fwd_slot=sel(fwd_slot),
        in_fwd_slot=sel(in_fwd_slot), stash_size=stash_size,
    )


def build_sync_slot_tables(num_microbatches: int, num_stages: int) -> SlotTables:
    """The *synchronous* 1F1B timetable driving the single-jit engine: every
    tick, every stage runs one forward **and** one backward (on different
    microbatches), so an SPMD program needs no rank-divergent control flow
    around the collective-bearing stage compute — required because XLA
    collectives inside a ``lax.cond`` deadlock when their participant set is
    not a subset of the branch takers.

    Closed form (stage ``s`` of ``P``, microbatch ``m`` of ``M``):

    - forward of ``m`` at tick ``s + m``;
    - backward of ``m`` at tick ``2(P-1) - s + m``;

    giving ``T = M + 2(P-1)`` ticks.  Dependency check: ``bwd(s, m)`` needs
    ``bwd(s+1, m)`` (tick ``2(P-1)-s-1+m``, one earlier) and ``fwd(s, m)``
    (tick ``s+m``, earlier — equal only at the last stage, where the tick
    body runs its forward before its backward).  In-flight microbatches at
    stage ``s`` = ``2(P-1-s) + 1``: the same O(P) bound as classic 1F1B
    (which holds ``P - s``) at twice the constant, in exchange for bubble-
    free steady-state ticks; still independent of ``M`` — the point of 1F1B
    over fill-drain (reference ``pipeline/scheduler.py:141-273``)."""
    M, P = num_microbatches, num_stages
    T = M + 2 * (P - 1)
    fwd_rows = [
        [t - s if 0 <= t - s < M else -1 for t in range(T)] for s in range(P)
    ]
    bwd_rows = [
        [t - 2 * (P - 1) + s if 0 <= t - 2 * (P - 1) + s < M else -1 for t in range(T)]
        for s in range(P)
    ]
    return SlotTables(
        num_microbatches=M,
        num_stages=P,
        num_slots=T,
        fwd_mb=tuple(tuple(r) for r in fwd_rows),
        bwd_mb=tuple(tuple(r) for r in bwd_rows),
        # entry for mb m is written at fwd time and read at bwd time,
        # 2(P-1-s) ticks later; mod-K indexing needs K > that span.
        fwd_stash_size=2 * (P - 1) + 1,
        # incoming grad is consumed the tick after it arrives
        bwd_stash_size=2,
    )
