"""Pipeline parallelism (reference ``pipeline/`` — NxDPPModel, 1F1B scheduler,
partitioner, neighbor comm; SURVEY §2.7).

The TPU-native engine compiles the whole microbatch schedule into one jit
(:mod:`.engine`); the declarative schedules (:mod:`.scheduler`) verify the
task arithmetic and remain available for host-driven execution."""

from neuronx_distributed_tpu.pipeline.engine import (
    EMBED,
    HEAD,
    LAYERS,
    PipelinedModel,
    build_pipelined_model,
    make_pipelined_forward_fn,
    make_pipelined_loss_fn,
    microbatch,
    stacked_layer_specs,
)
from neuronx_distributed_tpu.pipeline.partition import (
    layers_per_stage,
    partition_uniform,
    spans_from_cuts,
)
from neuronx_distributed_tpu.pipeline.scheduler import (
    BackwardStep,
    ForwardStep,
    InferenceSchedule,
    PipeSchedule,
    RecvBackward,
    RecvForward,
    ReduceGrads,
    SendBackward,
    SendForward,
    TrainSchedule,
    bubble_fraction,
)

__all__ = [
    "EMBED",
    "HEAD",
    "LAYERS",
    "PipelinedModel",
    "build_pipelined_model",
    "make_pipelined_loss_fn",
    "make_pipelined_forward_fn",
    "microbatch",
    "stacked_layer_specs",
    "partition_uniform",
    "spans_from_cuts",
    "layers_per_stage",
    "PipeSchedule",
    "TrainSchedule",
    "InferenceSchedule",
    "ForwardStep",
    "BackwardStep",
    "RecvForward",
    "SendForward",
    "RecvBackward",
    "SendBackward",
    "ReduceGrads",
    "bubble_fraction",
]
