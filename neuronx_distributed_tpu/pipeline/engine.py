"""Pipeline-parallel execution engine: the whole schedule in one jit.

TPU-native replacement for the reference's eager per-task PP runtime
(``pipeline/model.py``: ``NxDPPModel`` task executor ``:954-979``, fwd/bwd
tasks ``:637-920``, neighbor transport ``pipeline/comm.py:27-68``).  The
reference dispatches one lazy-tensor graph per task and moves activations
with 2-rank all-reduces bracketed by ``mark_step``; here the *entire*
microbatch schedule compiles into a single ``lax.scan`` inside a
partial-manual ``jax.shard_map``:

- the ``pp`` mesh axis is manual: each tick rotates stage outputs to the next
  stage with one ``lax.ppermute`` (a true collective-permute — what the
  reference emulates with paired all-reduce, ``comm.py:38-68``);
- every other axis (dp/tp/kvr/cp/ep) stays automatic, so the TP/SP layers'
  GSPMD sharding constraints keep working unchanged inside a stage;
- the backward pipeline needs no hand-written schedule at all: autodiff of
  ``scan`` + ``ppermute`` produces the reverse-order drain with transposed
  permutes, and XLA's latency-hiding scheduler overlaps the transfers.

Layer parameters are stacked on a leading layer axis sharded over ``pp``
(``L = num_stages * layers_per_stage``), so "partitioning" is a sharding
spec, not a graph split (see :mod:`..pipeline.partition`).  Non-stage
parameters (embedding, lm head, final norm) are replicated along ``pp``;
because the shard_map transpose psums gradients of replicated inputs over
``pp``, tied embedding/head weights need none of the reference's dedicated
shared-weight process groups (``parallel_state.py:347-379``).

Schedule shape: fill-drain over ``T = M + P - 1`` ticks (GPipe-style; the
1F1B reordering in :mod:`.scheduler` has identical bubble fraction and only
changes *eager* peak memory — under one jit, peak memory is governed by the
remat policy instead).  Known redundancy: embedding and head/loss run every
tick on every stage (masked to the owning stage), costing roughly
``(V / 6H) / layers_per_stage`` extra compute; acceptable next to the
(P-1)/(M+P-1) bubble and avoids materializing all microbatch outputs.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from neuronx_distributed_tpu.parallel.mesh import BATCH_AXES, PIPELINE_AXIS, get_mesh
from neuronx_distributed_tpu.pipeline.partition import layers_per_stage

# Param-tree keys understood by the engine.
EMBED = "embed"
LAYERS = "layers"
HEAD = "head"

BlockFn = Callable[[Any, jax.Array], jax.Array]
EmbedFn = Callable[[Any, jax.Array], jax.Array]
# head_loss_fn(head_params, hidden, labels) -> (loss_sum, token_count)
HeadLossFn = Callable[[Any, jax.Array, jax.Array], Tuple[jax.Array, jax.Array]]


def microbatch(x: jax.Array, num_microbatches: int, mesh: Optional[Mesh] = None) -> jax.Array:
    """[B, ...] -> [M, B/M, ...] (the reference's microbatch split,
    ``pipeline/model.py:560-580``).

    No sharding constraint is applied: a constraint on an operand feeding a
    partial-manual shard_map trips an XLA SPMD-partitioner CHECK (observed on
    XLA/jax 0.9), and none is needed — when dp divides the microbatch size,
    the dp-contiguous blocks of the global batch dim land exactly on the
    inner dim, so GSPMD propagates ``P(None, dp, ...)`` through the reshape
    on its own."""
    if x.shape[0] % num_microbatches != 0:
        raise ValueError(
            f"batch size {x.shape[0]} not divisible by num_microbatches {num_microbatches}"
        )
    del mesh
    return x.reshape(num_microbatches, x.shape[0] // num_microbatches, *x.shape[1:])


def stacked_layer_specs(block_specs: Any) -> Any:
    """Prepend the pp axis to per-block param specs: a block kernel spec
    ``P(None, 'tp')`` becomes ``P('pp', None, 'tp')`` for the [L, ...] stack."""
    return jax.tree.map(
        lambda s: P(PIPELINE_AXIS, *s), block_specs, is_leaf=lambda x: isinstance(x, P)
    )


def make_pipelined_loss_fn(
    embed_fn: EmbedFn,
    block_fn: BlockFn,
    head_loss_fn: HeadLossFn,
    num_microbatches: int,
    mesh: Optional[Mesh] = None,
    remat_block: bool = True,
    remat_policy: Optional[Callable] = None,
):
    """Build ``loss_fn(params, ids, labels) -> (loss_sum, token_count)``.

    ``params`` must be ``{EMBED: ..., LAYERS: stacked [L, ...], HEAD: ...}``.
    The returned function is differentiable and jittable; wrap its mean in
    ``jax.value_and_grad`` for training (the trainer does this).
    """
    mesh = mesh if mesh is not None else get_mesh()
    pp = mesh.shape[PIPELINE_AXIS]

    blk = block_fn
    if remat_block:
        blk = jax.checkpoint(block_fn, policy=remat_policy, prevent_cse=False)

    def stage_fn(stage_params, x):
        def body(h, layer_params):
            return blk(layer_params, h), None

        x, _ = lax.scan(body, x, stage_params)
        return x

    def loss_fn(params, ids: jax.Array, labels: jax.Array):
        """ids/labels: [B, S] global batch."""
        ids_mb = microbatch(ids, num_microbatches, mesh)
        labels_mb = microbatch(labels, num_microbatches, mesh)
        L = jax.tree.leaves(params[LAYERS])[0].shape[0]
        layers_per_stage(L, pp)  # validate divisibility

        if pp == 1:
            # Degenerate case: no pipeline machinery, plain scan over layers.
            def one_mb(carry, mb):
                i, l = mb
                x = stage_fn(params[LAYERS], embed_fn(params[EMBED], i))
                ls, n = head_loss_fn(params[HEAD], x, l)
                s, c = carry
                return (s + ls, c + n), None

            (loss_sum, tok), _ = lax.scan(
                one_mb, (jnp.zeros((), jnp.float32), jnp.zeros((), jnp.float32)),
                (ids_mb, labels_mb),
            )
            return loss_sum, tok

        M = num_microbatches
        T = M + pp - 1

        def f(layer_stack, embed_params, head_params, ids_mb, labels_mb):
            # layer_stack leaves are the local [L/pp, ...] slice.
            rank = lax.axis_index(PIPELINE_AXIS)
            is_first = rank == 0
            is_last = rank == pp - 1

            mb_shape = ids_mb.shape[1:]
            probe = jax.eval_shape(embed_fn, embed_params, jnp.zeros(mb_shape, ids_mb.dtype))

            def tick(carry, t):
                buf, loss_sum, tok_sum = carry
                feed_t = jnp.clip(t, 0, M - 1)
                ids_t = lax.dynamic_index_in_dim(ids_mb, feed_t, axis=0, keepdims=False)
                x0 = embed_fn(embed_params, ids_t)
                x_in = jnp.where(is_first, x0, buf)

                y = stage_fn(layer_stack, x_in)

                out_t = t - (pp - 1)
                lbl = lax.dynamic_index_in_dim(
                    labels_mb, jnp.clip(out_t, 0, M - 1), axis=0, keepdims=False
                )
                ls, n = head_loss_fn(head_params, y, lbl)
                use = jnp.logical_and(is_last, out_t >= 0)
                loss_sum = loss_sum + jnp.where(use, ls, 0.0).astype(jnp.float32)
                tok_sum = tok_sum + jnp.where(use, n, 0.0).astype(jnp.float32)

                nxt = lax.ppermute(
                    y, PIPELINE_AXIS, [(i, (i + 1) % pp) for i in range(pp)]
                )
                return (nxt, loss_sum, tok_sum), None

            init = (
                jnp.zeros(probe.shape, probe.dtype),
                jnp.zeros((), jnp.float32),
                jnp.zeros((), jnp.float32),
            )
            (_, loss_sum, tok_sum), _ = lax.scan(tick, init, jnp.arange(T))
            # only the last stage accumulated; make the result pp-invariant
            loss_sum = lax.psum(loss_sum, PIPELINE_AXIS)
            tok_sum = lax.psum(tok_sum, PIPELINE_AXIS)
            return loss_sum, tok_sum

        shmap = jax.shard_map(
            f,
            mesh=mesh,
            in_specs=(P(PIPELINE_AXIS), P(), P(), P(), P()),
            out_specs=(P(), P()),
            axis_names=frozenset({PIPELINE_AXIS}),
            check_vma=False,
        )
        return shmap(params[LAYERS], params[EMBED], params[HEAD], ids_mb, labels_mb)

    return loss_fn


@dataclasses.dataclass
class PipelinedModel:
    """Facade over a pipeline-staged model (the PP analogue of the trainer's
    ``ParallelModel``; reference ``NxDPPModel``, ``pipeline/model.py:45``).

    ``loss_fn(params, ids, labels) -> (loss_sum, token_count)`` runs the full
    microbatch schedule; ``forward_fn(params, ids) -> logits`` is the
    fwd-only path."""

    params: Any
    param_specs: Any
    mesh: Mesh
    num_microbatches: int
    loss_fn: Callable
    forward_fn: Callable

    @property
    def param_shardings(self):
        return jax.tree.map(
            lambda s: NamedSharding(self.mesh, s),
            self.param_specs,
            is_leaf=lambda x: isinstance(x, P),
        )

    def num_parameters(self) -> int:
        return sum(int(x.size) for x in jax.tree.leaves(self.params))


def build_pipelined_model(
    embed_fn: EmbedFn,
    block_fn: BlockFn,
    head_loss_fn: HeadLossFn,
    head_fn: Callable[[Any, jax.Array], jax.Array],
    embed_init: Callable[[jax.Array], Any],
    block_init: Callable[[jax.Array], Any],
    head_init: Callable[[jax.Array], Any],
    num_layers: int,
    num_microbatches: int,
    mesh: Optional[Mesh] = None,
    remat_block: bool = True,
    remat_policy: Optional[Callable] = None,
    seed: int = 0,
) -> PipelinedModel:
    """Initialize a pipelined model with stage parameters born sharded.

    ``*_init`` are flax ``Module.init`` thunks taking a PRNG key and
    returning a (possibly Partitioned-boxed) variable dict; block params are
    initialized per-layer under ``vmap`` into the stacked ``[L, ...]`` layout
    and placed pp-sharded (the GSPMD replacement for the reference's
    partition + sequential materialize-and-move,
    ``pipeline/model.py:1111-1125``)."""
    from flax import linen as nn

    mesh = mesh if mesh is not None else get_mesh()
    pp = mesh.shape[PIPELINE_AXIS]
    layers_per_stage(num_layers, pp)

    rng = jax.random.PRNGKey(seed)
    r_embed, r_head, r_layers = jax.random.split(rng, 3)

    def _params_of(tree):
        return tree["params"] if isinstance(tree, dict) and "params" in tree else tree

    def _specs_of(init, key):
        abs_tree = jax.eval_shape(init, key)
        return _params_of(nn.get_partition_spec(abs_tree))

    embed_specs = _specs_of(embed_init, r_embed)
    head_specs = _specs_of(head_init, r_head)
    block_specs = _specs_of(block_init, r_layers)
    layer_specs = stacked_layer_specs(block_specs)

    def _shardings(specs):
        return jax.tree.map(
            lambda s: NamedSharding(mesh, s), specs, is_leaf=lambda x: isinstance(x, P)
        )

    embed_params = jax.jit(
        lambda r: _params_of(nn.unbox(embed_init(r))), out_shardings=_shardings(embed_specs)
    )(r_embed)
    head_params = jax.jit(
        lambda r: _params_of(nn.unbox(head_init(r))), out_shardings=_shardings(head_specs)
    )(r_head)
    layer_keys = jax.random.split(r_layers, num_layers)
    layer_params = jax.jit(
        lambda ks: jax.vmap(lambda k: _params_of(nn.unbox(block_init(k))))(ks),
        out_shardings=_shardings(layer_specs),
    )(layer_keys)

    params = {EMBED: embed_params, LAYERS: layer_params, HEAD: head_params}
    specs = {EMBED: embed_specs, LAYERS: layer_specs, HEAD: head_specs}

    loss_fn = make_pipelined_loss_fn(
        embed_fn,
        block_fn,
        head_loss_fn,
        num_microbatches,
        mesh=mesh,
        remat_block=remat_block,
        remat_policy=remat_policy,
    )
    forward_fn = make_pipelined_forward_fn(
        embed_fn, block_fn, head_fn, num_microbatches, mesh=mesh
    )
    return PipelinedModel(
        params=params,
        param_specs=specs,
        mesh=mesh,
        num_microbatches=num_microbatches,
        loss_fn=loss_fn,
        forward_fn=forward_fn,
    )


def make_pipelined_forward_fn(
    embed_fn: EmbedFn,
    block_fn: BlockFn,
    head_fn: Callable[[Any, jax.Array], jax.Array],
    num_microbatches: int,
    mesh: Optional[Mesh] = None,
):
    """Forward-only pipeline (the reference's ``InferenceSchedule`` path,
    ``pipeline/model.py:run_eval``): returns ``fn(params, ids) -> outputs``
    with outputs stacked back to the global batch.

    Implementation: the hidden states exiting the last stage are collected
    per tick and broadcast from the last stage once at the end (one transfer,
    not one per microbatch), then the head runs under plain GSPMD.
    """
    mesh = mesh if mesh is not None else get_mesh()
    pp = mesh.shape[PIPELINE_AXIS]

    def stage_fn(stage_params, x):
        def body(h, layer_params):
            return block_fn(layer_params, h), None

        x, _ = lax.scan(body, x, stage_params)
        return x

    def forward_fn(params, ids: jax.Array):
        ids_mb = microbatch(ids, num_microbatches, mesh)
        M = num_microbatches

        if pp == 1:
            def one_mb(_, i):
                return None, head_fn(params[HEAD], stage_fn(params[LAYERS], embed_fn(params[EMBED], i)))

            _, outs = lax.scan(one_mb, None, ids_mb)
            return outs.reshape(ids.shape[0], *outs.shape[2:])

        T = M + pp - 1

        def f(layer_stack, embed_params, ids_mb):
            rank = lax.axis_index(PIPELINE_AXIS)
            is_first = rank == 0
            is_last = rank == pp - 1
            mb_shape = ids_mb.shape[1:]
            probe = jax.eval_shape(embed_fn, embed_params, jnp.zeros(mb_shape, ids_mb.dtype))

            def tick(carry, t):
                buf, outs = carry
                feed_t = jnp.clip(t, 0, M - 1)
                ids_t = lax.dynamic_index_in_dim(ids_mb, feed_t, axis=0, keepdims=False)
                x_in = jnp.where(is_first, embed_fn(embed_params, ids_t), buf)
                y = stage_fn(layer_stack, x_in)
                out_t = t - (pp - 1)
                write = jnp.where(jnp.logical_and(is_last, out_t >= 0), y, 0.0).astype(y.dtype)
                outs = lax.dynamic_update_index_in_dim(
                    outs, outs[jnp.clip(out_t, 0, M - 1)] + write, jnp.clip(out_t, 0, M - 1), axis=0
                )
                nxt = lax.ppermute(y, PIPELINE_AXIS, [(i, (i + 1) % pp) for i in range(pp)])
                return (nxt, outs), None

            init = (
                jnp.zeros(probe.shape, probe.dtype),
                jnp.zeros((M, *probe.shape), probe.dtype),
            )
            (_, outs), _ = lax.scan(tick, init, jnp.arange(T))
            # gather the last stage's buffer to every pp rank (single psum —
            # all other ranks contributed zeros)
            return lax.psum(outs, PIPELINE_AXIS)

        shmap = jax.shard_map(
            f,
            mesh=mesh,
            in_specs=(P(PIPELINE_AXIS), P(), P()),
            out_specs=P(),
            axis_names=frozenset({PIPELINE_AXIS}),
            check_vma=False,
        )
        hidden = shmap(params[LAYERS], params[EMBED], ids_mb)
        logits = head_fn(params[HEAD], hidden.reshape(ids.shape[0], *hidden.shape[2:]))
        return logits

    return forward_fn
