"""Pipeline-parallel execution engine: the whole schedule in one jit.

TPU-native replacement for the reference's eager per-task PP runtime
(``pipeline/model.py``: ``NxDPPModel`` task executor ``:954-979``, fwd/bwd
tasks ``:637-920``, neighbor transport ``pipeline/comm.py:27-68``).  The
reference dispatches one lazy-tensor graph per task and moves activations
with 2-rank all-reduces bracketed by ``mark_step``; here the *entire*
microbatch schedule compiles into a single ``lax.scan`` inside a
partial-manual ``jax.shard_map``:

- the ``pp`` mesh axis is manual: each tick rotates stage outputs to the next
  stage with one ``lax.ppermute`` (a true collective-permute — what the
  reference emulates with paired all-reduce, ``comm.py:38-68``);
- every other axis (dp/tp/kvr/cp/ep) stays automatic, so the TP/SP layers'
  GSPMD sharding constraints keep working unchanged inside a stage;
- the backward pipeline needs no hand-written schedule at all: autodiff of
  ``scan`` + ``ppermute`` produces the reverse-order drain with transposed
  permutes, and XLA's latency-hiding scheduler overlaps the transfers.

Layer parameters are stacked on a leading layer axis sharded over ``pp``
(``L = num_stages * layers_per_stage``), so "partitioning" is a sharding
spec, not a graph split (see :mod:`..pipeline.partition`).  Non-stage
parameters (embedding, lm head, final norm) are replicated along ``pp``;
because the shard_map transpose psums gradients of replicated inputs over
``pp``, tied embedding/head weights need none of the reference's dedicated
shared-weight process groups (``parallel_state.py:347-379``).

Two schedules are provided:

- :func:`make_pipelined_loss_fn` — differentiable fill-drain (GPipe) over
  ``T = M + P - 1`` ticks; autodiff of the scan stores residuals for all
  ``T`` ticks, so peak activation memory grows with ``M``.  Kept as the
  differentiable oracle and for ``schedule="gpipe"``.
- :func:`make_1f1b_loss_and_grad_fn` — the production path
  (``schedule="1f1b"``): manual backward with a circular activation stash
  bounded by ``2(P-1)+1`` microbatches, independent of ``M`` — the 1F1B
  memory property of the reference's ``TrainSchedule``
  (``pipeline/scheduler.py:141-273``), realized as a synchronous
  one-forward-plus-one-backward tick.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from neuronx_distributed_tpu.parallel.mesh import (
    BATCH_AXES,
    DATA_AXIS,
    EXPERT_AXIS,
    PIPELINE_AXIS,
    get_mesh,
)
from neuronx_distributed_tpu.pipeline.partition import (
    layers_per_stage,
    padded_layer_layout,
)
from neuronx_distributed_tpu.pipeline.scheduler import build_sync_slot_tables
from neuronx_distributed_tpu.utils.common import shard_map as _shard_map

# Param-tree keys understood by the engine.
EMBED = "embed"
LAYERS = "layers"
HEAD = "head"


def _make_cact(act_spec):
    """Closure pinning an activation to ``act_spec`` over the context mesh
    (identity when no spec).  Used wherever a ``lax.cond``/``where`` branch
    bypasses the model: XLA requires both branches identically sharded, and
    the model's own branch constrains its output internally under SP."""
    if act_spec is None:
        return lambda a: a
    from neuronx_distributed_tpu.parallel.layers import shard_activation

    return lambda a: shard_activation(a, act_spec)


def _make_stage_fn(blk, layer_mask, block_aux: bool = False, act_spec: Optional[P] = None):
    """Stage executor: scan the stage's layer rows; returns ``(x, aux)``.

    ``layer_mask`` (``[L']`` of 0/1, or None) marks padded rows added for a
    non-divisible layer count or uneven ``pipeline_cuts``
    (:func:`..partition.layout_from_spans`): a padded row runs under
    ``lax.cond(active, block, identity)``, so it costs (almost) nothing —
    which is what makes uneven cuts an actual *rebalancing* tool: a stage
    holding fewer real layers genuinely finishes its tick earlier.  The
    predicate is legal for the same reason as the engines' embed/head conds:
    it depends only on the pp rank (the mask is a compile-time constant
    sliced by ``axis_index``), and the manual axes carry no GSPMD
    collectives, so every participant of any auto-axis collective channel
    inside the block takes the same branch.  The cond's vjp zeroes the
    padded rows' (zero-initialized) parameter gradients.  The mask is NOT a
    parameter — it must never reach the optimizer or checkpoints.

    ``act_spec`` pins both cond branches' output sharding (the block
    constrains its output internally under SP; the identity branch must
    match or the partitioner rejects the conditional).

    ``block_aux``: the block returns ``(y, aux_scalar)`` (e.g. a MoE
    load-balancing term) and ``aux`` is the sum over the stage's live
    layers; otherwise ``aux`` is a constant 0 (folded away by XLA).

    The masked ``stage_fn`` also accepts an optional ``mask_local``
    argument overriding the rank-sliced constant — the interleaved engine
    passes its own (rank, chunk)-sliced mask (rows ``rank*(V*per) +
    v*per``), which the contiguous ``rank*L_local`` slicing here cannot
    express; pass ``layer_mask="arg"`` to build that form with no
    constant."""

    cact = _make_cact(act_spec)

    def call(layer_params, h, extras):
        if block_aux:
            y, a = blk(layer_params, h, *extras)
            return y, a.astype(jnp.float32)
        return blk(layer_params, h, *extras), jnp.zeros((), jnp.float32)

    if layer_mask is None:
        def stage_fn(stage_params, x, extras=()):
            def body(carry, layer_params):
                h, aux = carry
                y, a = call(layer_params, h, extras)
                return (y, aux + a), None

            (x, aux), _ = lax.scan(body, (x, jnp.zeros((), jnp.float32)), stage_params)
            return x, aux

        return stage_fn

    mask_const = (None if isinstance(layer_mask, str)  # "arg": caller-supplied
                  else jnp.asarray(layer_mask, jnp.float32))

    def stage_fn(stage_params, x, extras=(), mask_local=None):
        if mask_local is not None:
            local = mask_local
        else:
            L_local = jax.tree.leaves(stage_params)[0].shape[0]
            if mask_const.shape[0] == L_local:
                local = mask_const  # pp == 1: the whole stack is local
            else:
                rank = lax.axis_index(PIPELINE_AXIS)
                local = lax.dynamic_slice_in_dim(mask_const, rank * L_local, L_local)

        def body(carry, xs):
            h, aux = carry
            layer_params, a = xs
            y, aux_l = lax.cond(
                a > 0,
                lambda lp, hh: (lambda o: (cact(o[0]), o[1]))(call(lp, hh, extras)),
                lambda lp, hh: (cact(hh), jnp.zeros((), jnp.float32)),
                layer_params, h,
            )
            return (y, aux + aux_l), None

        (x, aux), _ = lax.scan(body, (x, jnp.zeros((), jnp.float32)), (stage_params, local))
        return x, aux

    return stage_fn


BlockFn = Callable[[Any, jax.Array], jax.Array]
EmbedFn = Callable[[Any, jax.Array], jax.Array]
# head_loss_fn(head_params, hidden, labels) -> (loss_sum, token_count)
HeadLossFn = Callable[[Any, jax.Array, jax.Array], Tuple[jax.Array, jax.Array]]


def microbatch(x: jax.Array, num_microbatches: int, mesh: Optional[Mesh] = None) -> jax.Array:
    """[B, ...] -> [M, B/M, ...] (the reference's microbatch split,
    ``pipeline/model.py:560-580``).

    The microbatch size ``B/M`` must additionally be divisible by the
    data-parallel degree: the engines make dp a *manual* shard_map axis (the
    batch is split explicitly per dp rank), mirroring the reference's
    ``DistributedSampler`` contract of equal per-rank batches."""
    if x.shape[0] % num_microbatches != 0:
        raise ValueError(
            f"batch size {x.shape[0]} not divisible by num_microbatches {num_microbatches}"
        )
    mb = x.shape[0] // num_microbatches
    if mesh is not None:
        from neuronx_distributed_tpu.parallel.mesh import get_data_parallel_size

        dp = get_data_parallel_size(mesh)
        if mb % dp != 0:
            raise ValueError(
                f"microbatch size {mb} (batch {x.shape[0]} / {num_microbatches} "
                f"microbatches) must be divisible by the data-parallel degree {dp}"
            )
    return x.reshape(num_microbatches, mb, *x.shape[1:])


def stacked_layer_specs(block_specs: Any) -> Any:
    """Prepend the pp axis to per-block param specs: a block kernel spec
    ``P(None, 'tp')`` becomes ``P('pp', None, 'tp')`` for the [L, ...] stack."""
    return jax.tree.map(
        lambda s: P(PIPELINE_AXIS, *s), block_specs, is_leaf=lambda x: isinstance(x, P)
    )


def _spec_axes(s: P) -> frozenset:
    axes = set()
    for e in s:
        if e is None:
            continue
        if isinstance(e, (tuple, list)):
            axes.update(e)
        else:
            axes.add(e)
    return frozenset(axes)


def _layer_in_specs(layer_specs):
    """shard_map in/out specs for the layer stack: the caller's per-leaf
    stacked specs filtered down to the engine's manual axes (pp, and ep on
    MoE expert leaves — real expert sharding under PP); ``None`` gives the
    historical plain pp prefix.  Auto-axis names (tp/kvr/...) must not
    appear in a partial-manual shard_map spec — GSPMD keeps handling them
    inside."""
    if layer_specs is None:
        return P(PIPELINE_AXIS)
    keep = frozenset({PIPELINE_AXIS, EXPERT_AXIS})

    def filt(s: P) -> P:
        out = []
        for e in s:
            if isinstance(e, (tuple, list)):
                kept = tuple(a for a in e if a in keep)
                out.append(kept[0] if len(kept) == 1 else (kept or None))
            else:
                out.append(e if e in keep else None)
        return P(*out)

    return jax.tree.map(filt, layer_specs, is_leaf=lambda x: isinstance(x, P))


def _ep_psum_flags(layer_specs, params_tree):
    """True per leaf when its gradient must ALSO be psum'd over ep (the
    leaf is ep-replicated); expert-sharded leaves hold distinct shards per
    ep rank, whose grads arrive complete via the module's collectives."""
    if layer_specs is None:
        return jax.tree.map(lambda _: True, params_tree)
    return jax.tree.map(
        lambda s: EXPERT_AXIS not in _spec_axes(s),
        layer_specs, is_leaf=lambda x: isinstance(x, P),
    )


def make_pipelined_loss_fn(
    embed_fn: EmbedFn,
    block_fn: BlockFn,
    head_loss_fn: HeadLossFn,
    num_microbatches: int,
    mesh: Optional[Mesh] = None,
    remat_block: bool = True,
    remat_policy: Optional[Callable] = None,
    layer_mask=None,
    block_aux: bool = False,
    act_spec: Optional[P] = None,
    layer_specs: Any = None,
):
    """Build ``loss_fn(params, ids, labels) -> (loss_sum, token_count)``.

    ``params`` must be ``{EMBED: ..., LAYERS: stacked [L, ...], HEAD: ...}``.
    The returned function is differentiable and jittable; wrap its mean in
    ``jax.value_and_grad`` for training (the trainer does this).

    ``block_aux``: blocks return ``(y, aux)`` and the per-layer aux terms
    (e.g. MoE load balancing, coefficient already folded in by the caller)
    are *averaged* over layers × microbatches [× data-parallel ranks] and
    added to the reported mean loss — i.e. ``loss_sum`` gains
    ``mean(aux) * token_count`` so the trainer's ``loss_sum / tok``
    normalization reproduces ``ce_mean + mean(aux)``, matching the non-PP
    ``causal_lm_loss`` semantics.
    """
    mesh = mesh if mesh is not None else get_mesh()
    pp = mesh.shape[PIPELINE_AXIS]

    blk = block_fn
    if remat_block:
        blk = jax.checkpoint(block_fn, policy=remat_policy, prevent_cse=False)

    stage_fn = _make_stage_fn(blk, layer_mask, block_aux, act_spec)
    n_real_layers = (
        int(sum(layer_mask)) if layer_mask is not None else None  # else runtime L
    )

    def loss_fn(params, ids: jax.Array, labels: jax.Array, *extras):
        """ids/labels (+ per-token ``extras`` like positions/segment_ids,
        each [B, S], microbatched identically): global batch."""
        # dp divisibility only binds on the pp>1 shard_map path (manual dp
        # batch split); pp==1 runs under GSPMD auto sharding
        ids_mb = microbatch(ids, num_microbatches, mesh if pp > 1 else None)
        labels_mb = microbatch(labels, num_microbatches, mesh if pp > 1 else None)
        extras_mb = tuple(
            microbatch(e, num_microbatches, mesh if pp > 1 else None) for e in extras
        )
        L = jax.tree.leaves(params[LAYERS])[0].shape[0]
        layers_per_stage(L, pp)  # validate divisibility
        L_real = n_real_layers if n_real_layers is not None else L
        M = num_microbatches

        if pp == 1:
            # Degenerate case: no pipeline machinery, plain scan over layers.
            tok_total = jnp.sum((labels >= 0).astype(jnp.float32))

            def one_mb(carry, mb):
                i, l, *ex = mb
                x, aux = stage_fn(params[LAYERS], embed_fn(params[EMBED], i), tuple(ex))
                ls, n = head_loss_fn(params[HEAD], x, l)
                s, c = carry
                # aux: sum over layers for this microbatch; normalize to the
                # layer x microbatch mean, scaled by tokens so the caller's
                # /tok division recovers ce_mean + mean(aux)
                s = s + ls + aux * tok_total / (L_real * M)
                return (s, c + n), None

            (loss_sum, tok), _ = lax.scan(
                one_mb, (jnp.zeros((), jnp.float32), jnp.zeros((), jnp.float32)),
                (ids_mb, labels_mb, *extras_mb),
            )
            return loss_sum, tok

        T = M + pp - 1
        dpsz = mesh.shape[DATA_AXIS] * mesh.shape[EXPERT_AXIS]

        def f(layer_stack, embed_params, head_params, ids_mb, labels_mb, *extras_mb):
            # layer_stack leaves are the local [L/pp, ...] slice.
            rank = lax.axis_index(PIPELINE_AXIS)
            is_first = rank == 0
            is_last = rank == pp - 1
            # aux weight: global token count x the layer/microbatch/dp-mean
            # normalization (each dp rank computed aux on its batch shard;
            # labels_mb is the local slice, batch replicated along pp)
            tok_total = lax.psum(
                jnp.sum((labels_mb >= 0).astype(jnp.float32)), (DATA_AXIS, EXPERT_AXIS)
            )
            aux_w = tok_total / (L_real * M * dpsz)

            mb_shape = ids_mb.shape[1:]
            probe = jax.eval_shape(embed_fn, embed_params, jnp.zeros(mb_shape, ids_mb.dtype))

            cact = _make_cact(act_spec)

            def tick(carry, t):
                buf, loss_sum, tok_sum = carry
                feed_t = jnp.clip(t, 0, M - 1)
                ids_t = lax.dynamic_index_in_dim(ids_mb, feed_t, axis=0, keepdims=False)
                # embed/head run under lax.cond on their owning pp rank, not
                # uniformly-then-masked: the predicate is pp-only and the
                # manual axes carry no GSPMD collectives, so every member of
                # any auto-axis collective channel inside (tp/kvr/cp) takes
                # the same branch — see the 1F1B objective's note
                x0 = lax.cond(
                    is_first,
                    lambda ep: cact(embed_fn(ep, ids_t).astype(probe.dtype)),
                    lambda ep: cact(jnp.zeros(probe.shape, probe.dtype)),
                    embed_params,
                )
                x_in = jnp.where(is_first, x0, buf)

                # this stage computes microbatch t - rank; extras must come
                # from THAT microbatch (clipped on bubble ticks, masked out)
                my_t = jnp.clip(t - rank, 0, M - 1)
                ex_t = tuple(
                    lax.dynamic_index_in_dim(e, my_t, axis=0, keepdims=False)
                    for e in extras_mb
                )
                y, aux = stage_fn(layer_stack, x_in, ex_t)
                # bubble ticks run on garbage and their aux must not count
                fwd_valid = jnp.logical_and(t >= rank, t - rank < M)
                loss_sum = loss_sum + jnp.where(fwd_valid, aux, 0.0) * aux_w

                out_t = t - (pp - 1)
                lbl = lax.dynamic_index_in_dim(
                    labels_mb, jnp.clip(out_t, 0, M - 1), axis=0, keepdims=False
                )
                ls, n = lax.cond(
                    is_last,
                    lambda hp_, y_: tuple(
                        o.astype(jnp.float32) for o in head_loss_fn(hp_, y_, lbl)
                    ),
                    lambda hp_, y_: (jnp.zeros((), jnp.float32),
                                     jnp.zeros((), jnp.float32)),
                    head_params, y,
                )
                use = jnp.logical_and(is_last, out_t >= 0)
                loss_sum = loss_sum + jnp.where(use, ls, 0.0)
                tok_sum = tok_sum + jnp.where(use, n, 0.0)

                nxt = lax.ppermute(
                    y, PIPELINE_AXIS, [(i, (i + 1) % pp) for i in range(pp)]
                )
                return (nxt, loss_sum, tok_sum), None

            init = (
                jnp.zeros(probe.shape, probe.dtype),
                jnp.zeros((), jnp.float32),
                jnp.zeros((), jnp.float32),
            )
            (_, loss_sum, tok_sum), _ = lax.scan(tick, init, jnp.arange(T))
            # only the last stage accumulated ce (and each dp shard saw only
            # its batch slice); aux accumulated per stage — the pp psum sums
            # distinct stage contributions, the dp psum is averaged by aux_w
            loss_sum = lax.psum(loss_sum, (DATA_AXIS, EXPERT_AXIS, PIPELINE_AXIS))
            tok_sum = lax.psum(tok_sum, (DATA_AXIS, EXPERT_AXIS, PIPELINE_AXIS))
            return loss_sum, tok_sum

        # dp/ep are manual alongside pp: the batch dim is split explicitly
        # (auto-dp batch sharding under a partial-manual shard_map trips an
        # XLA SPMD-partitioner CHECK when SP constraints are present), and
        # the shard_map transpose psums parameter cotangents over dp — the
        # explicit form of the reference's bucketed DP grad all-reduce
        # (grads.py:193-246).
        shmap = _shard_map(
            f,
            mesh=mesh,
            in_specs=(_layer_in_specs(layer_specs), P(), P(),
                      P(None, BATCH_AXES), P(None, BATCH_AXES),
                      *[P(None, BATCH_AXES)] * len(extras)),
            out_specs=(P(), P()),
            axis_names=frozenset({DATA_AXIS, EXPERT_AXIS, PIPELINE_AXIS}),
            check_vma=False,
        )
        return shmap(params[LAYERS], params[EMBED], params[HEAD], ids_mb, labels_mb,
                     *extras_mb)

    return loss_fn


def make_1f1b_loss_and_grad_fn(
    embed_fn: EmbedFn,
    block_fn: BlockFn,
    head_loss_fn: HeadLossFn,
    num_microbatches: int,
    mesh: Optional[Mesh] = None,
    remat_block: bool = True,
    remat_policy: Optional[Callable] = None,
    act_spec: Optional[P] = None,
    layer_mask=None,
    block_aux: bool = False,
    layer_specs: Any = None,
):
    """Build ``fn(params, ids, labels) -> ((loss_sum, token_count), grads)``
    running the true 1F1B schedule in one jit — the production PP train path
    (reference ``TrainSchedule`` 1F1B, ``pipeline/scheduler.py:141-273``).

    Unlike :func:`make_pipelined_loss_fn` (whose fill-drain scan is
    differentiated by autodiff, storing residuals for all ``M + P - 1``
    ticks), this computes gradients *manually* inside the scan with bounded
    state, exactly like the reference's eager 1F1B executor:

    - a circular **activation stash** of ``2(P-1)+1`` microbatch inputs per
      stage (the 1F1B in-flight bound — O(P), independent of ``M``)
      replaces autodiff residuals; the backward recomputes the stage forward
      under ``jax.vjp`` from the stashed input (activation recomputation);
    - the timetable is the *synchronous* 1F1B of
      :func:`..scheduler.build_sync_slot_tables`: every tick, every stage
      runs one forward and one backward, **uniformly across ranks** — no
      rank-divergent ``lax.cond`` anywhere.  This is a hard constraint, not
      a style choice: GSPMD freely inserts reshard collective-permutes
      (e.g. for the GQA kvr regroup or SP gathers) whose channel spans the
      whole mesh, and any collective inside a branch not taken by every
      channel participant deadlocks — observed on XLA:CPU and equally true
      of TPU executables;
    - uniformity means embedding and head+loss run every tick on every rank
      (their results masked by ``where``).  The embedding is a cheap gather;
      the head costs ``2hV / (layers_per_stage * (8h² + 6hi))`` extra compute
      (≈8% for a 7B/PP4 shape, ≈1% for 70B/PP4 —
      ``scheduler.sync_1f1b_head_overhead``) — the price of deadlock-freedom,
      paid only on the PP path.  The schedule itself runs ``T = M + 2(P-1)``
      full fwd+bwd ticks for ``M`` useful pairs — ~2x the eager-1F1B bubble
      at equal M (``scheduler.bubble_fraction(..., "sync_1f1b")``), amortizing
      identically with large M; measured against fill-drain autodiff it is
      nonetheless equal-or-faster wall-clock at M >= 8 because its O(P)
      circular stash replaces residuals that grow with M
      (``docs/PP_SCHEDULE_NOTES.md``).  The backward is one uniform ``jax.vjp`` of a
      scalar-``where`` objective: the real loss on the last rank, an
      inner product ``sum(y * g_in)`` injecting the incoming cotangent on
      the others — the select's transpose zeroes head grads off the last
      rank automatically;
    - gradients accumulate in param dtype; embed/head grads (masked to
      their owning stage) are psum'd over ``pp`` at the end, which is also
      what makes tied weights correct with no dedicated process groups
      (reference ``parallel_state.py:347-379``).

    ``act_spec`` is the inter-stage activation PartitionSpec (e.g. the
    sequence-parallel residual sharding).  It must be supplied whenever the
    model annotates activations with explicit sharding constraints: XLA
    requires every ``lax.cond``'s branches to produce identically-sharded
    results, so the engine re-applies the same constraint on the branches
    that bypass the model (stash reads, zero fills).
    """
    mesh = mesh if mesh is not None else get_mesh()
    pp = mesh.shape[PIPELINE_AXIS]
    M = num_microbatches

    blk = block_fn
    if remat_block:
        blk = jax.checkpoint(block_fn, policy=remat_policy, prevent_cse=False)

    stage_fn = _make_stage_fn(blk, layer_mask, block_aux, act_spec)
    n_real_layers = int(sum(layer_mask)) if layer_mask is not None else None

    if pp == 1:
        # no pipeline: autodiff the plain microbatched loss
        plain = make_pipelined_loss_fn(
            embed_fn, block_fn, head_loss_fn, M, mesh=mesh,
            remat_block=remat_block, remat_policy=remat_policy,
            layer_mask=layer_mask, block_aux=block_aux, act_spec=act_spec,
        )

        def loss_and_grad_pp1(params, ids, labels, *extras):
            (loss_sum, tok), grads = jax.value_and_grad(plain, has_aux=True)(
                params, ids, labels, *extras
            )
            return (loss_sum, tok), grads

        return loss_and_grad_pp1

    tables = build_sync_slot_tables(M, pp)
    T = tables.num_slots
    Kf = tables.fwd_stash_size
    Kb = tables.bwd_stash_size
    import numpy as np

    fwd_tab = np.asarray(tables.fwd_mb, np.int32)          # [P, T]
    bwd_tab = np.asarray(tables.bwd_mb, np.int32)          # [P, T]
    in_fwd_tab = np.full_like(fwd_tab, -1)
    in_fwd_tab[1:] = fwd_tab[:-1]                          # arrival of fwd acts
    in_bwd_tab = np.full_like(bwd_tab, -1)
    in_bwd_tab[:-1] = bwd_tab[1:]                          # arrival of grads

    fwd_perm = [(i, (i + 1) % pp) for i in range(pp)]
    bwd_perm = [(i, (i - 1) % pp) for i in range(pp)]

    def loss_and_grad(params, ids: jax.Array, labels: jax.Array, *extras):
        ids_mb = microbatch(ids, M, mesh if pp > 1 else None)
        labels_mb = microbatch(labels, M, mesh if pp > 1 else None)
        extras_mb = tuple(microbatch(e, M, mesh if pp > 1 else None) for e in extras)
        L = jax.tree.leaves(params[LAYERS])[0].shape[0]
        layers_per_stage(L, pp)  # validate divisibility

        L_real = n_real_layers if n_real_layers is not None else L
        dpsz = mesh.shape[DATA_AXIS] * mesh.shape[EXPERT_AXIS]

        def f(layer_stack, embed_params, head_params, ids_mb, labels_mb, *extras_mb):
            rank = lax.axis_index(PIPELINE_AXIS)
            is_first = rank == 0
            is_last = rank == pp - 1
            # MoE-style aux normalization — see make_pipelined_loss_fn
            tok_total = lax.psum(
                jnp.sum((labels_mb >= 0).astype(jnp.float32)), (DATA_AXIS, EXPERT_AXIS)
            )
            aux_w = tok_total / (L_real * M * dpsz)

            mb_shape = ids_mb.shape[1:]
            probe = jax.eval_shape(
                embed_fn, embed_params, jnp.zeros(mb_shape, ids_mb.dtype)
            )
            act = jax.ShapeDtypeStruct(probe.shape, probe.dtype)

            cact = _make_cact(act_spec)

            my_f = jnp.take(jnp.asarray(fwd_tab), rank, axis=0)
            my_b = jnp.take(jnp.asarray(bwd_tab), rank, axis=0)
            in_f = jnp.take(jnp.asarray(in_fwd_tab), rank, axis=0)
            in_b = jnp.take(jnp.asarray(in_bwd_tab), rank, axis=0)

            def masked_add(acc, delta, flag):
                """acc += delta where flag, NaN-safe on garbage slots."""
                return jax.tree.map(
                    lambda a, d: a + jnp.where(flag, d, jnp.zeros_like(d)), acc, delta
                )

            def tick(carry, xs):
                stash, gstash, gl, ge, gh, loss_sum, tok_sum = carry
                mf, mb, inf, inb = xs
                # the STAGE compute runs uniformly every tick (bubble slots
                # compute on garbage and are masked out): a rank-and-tick-
                # varying cond around stage_fn would put the tick's ppermutes
                # behind divergent control flow — forbidden.  The embed/head
                # conds below are different: their collectives span only auto
                # axes, whose members all share one pp rank (see objective).
                do_f = mf >= 0
                do_b = mb >= 0

                # ---------- forward part ----------
                ids_f = lax.dynamic_index_in_dim(ids_mb, mf, 0, keepdims=False)
                # embed only where its result is consumed (stage 0) — same
                # pp-uniform-predicate argument as the head cond below
                x_emb = lax.cond(
                    is_first,
                    lambda ep: cact(embed_fn(ep, ids_f).astype(act.dtype)),
                    lambda ep: cact(jnp.zeros(act.shape, act.dtype)),
                    embed_params,
                )
                x_stash = cact(
                    lax.dynamic_index_in_dim(stash, mf % Kf, 0, keepdims=False)
                )
                x_in = jnp.where(is_first, x_emb, x_stash)
                # stage 0 stashes its input for the backward (other stages
                # rewrite the identical received value); bubbles must not
                # clobber a live entry.
                stash = lax.dynamic_update_index_in_dim(
                    stash, jnp.where(do_f, x_in, x_stash), mf % Kf, 0
                )
                ex_f = tuple(
                    lax.dynamic_index_in_dim(e, jnp.maximum(mf, 0), 0, keepdims=False)
                    for e in extras_mb
                )
                y, _ = stage_fn(layer_stack, x_in, ex_f)  # aux counted in the bwd
                y = cact(y)

                # ---------- backward part ----------
                x_b = lax.dynamic_index_in_dim(stash, mb % Kf, 0, keepdims=False)
                g_in = lax.dynamic_index_in_dim(gstash, mb % Kb, 0, keepdims=False)
                lbl = lax.dynamic_index_in_dim(labels_mb, mb, 0, keepdims=False)
                ids_b = lax.dynamic_index_in_dim(ids_mb, mb, 0, keepdims=False)
                ex_b = tuple(
                    lax.dynamic_index_in_dim(e, jnp.maximum(mb, 0), 0, keepdims=False)
                    for e in extras_mb
                )

                def objective(lp, hp, xx):
                    """Last stage: the real loss.  Middle stages: <y, g_in>,
                    whose vjp injects the incoming cotangent.  Every stage
                    additionally adds its own (normalized) block-aux term,
                    so aux gradients flow without any extra channel.

                    The head+loss runs under ``lax.cond(is_last, ...)`` — NOT
                    uniformly-then-masked: the predicate depends only on the
                    pp rank, and inside this shard_map the manual axes
                    (dp/ep/pp) carry no GSPMD-inserted collectives, so every
                    participant of any auto-axis collective channel the head
                    contains (tp/kvr/cp — e.g. the SP seq-gather, the
                    vocab-parallel loss psums) shares one pp rank and takes
                    the same branch.  This removes the per-tick head tax on
                    P-1 of P ranks (``scheduler.sync_1f1b_head_overhead``);
                    combine with ``pipeline_cuts`` giving the last stage
                    fewer layers to rebalance the tick critical path.  The
                    cond's vjp zeroes head grads on non-last ranks."""
                    yy, aux = stage_fn(lp, xx, ex_b)
                    ls, n = lax.cond(
                        is_last,
                        lambda hp_, yy_: tuple(
                            o.astype(jnp.float32) for o in head_loss_fn(hp_, yy_, lbl)
                        ),
                        lambda hp_, yy_: (jnp.zeros((), jnp.float32),
                                          jnp.zeros((), jnp.float32)),
                        hp, yy,
                    )
                    dot = jnp.sum(yy.astype(jnp.float32) * g_in.astype(jnp.float32))
                    obj = jnp.where(is_last, ls, dot) + aux_w * aux
                    return obj, (ls, n, aux.astype(jnp.float32))

                (obj, (ls, n, aux_b)), vjp_fn = jax.vjp(
                    lambda lp, hp, xx: objective(lp, hp, xx), layer_stack,
                    head_params, x_b, has_aux=False,
                )
                zero = jnp.zeros((), jnp.float32)
                dl, dh, dx = vjp_fn((jnp.ones((), jnp.float32), (zero, zero, zero)))
                dx = cact(dx)

                # embedding backward (a vocab-sized scatter-add) only on the
                # stage that owns it, and only on live slots
                de = lax.cond(
                    jnp.logical_and(do_b, is_first),
                    lambda ep: jax.vjp(
                        lambda e: embed_fn(e, ids_b).astype(act.dtype), ep
                    )[1](dx)[0],
                    lambda ep: jax.tree.map(jnp.zeros_like, ep),
                    embed_params,
                )

                gl = masked_add(gl, dl, do_b)
                gh = masked_add(gh, dh, do_b)
                ge = jax.tree.map(jnp.add, ge, de)  # cond already zeroes
                use = jnp.logical_and(do_b, is_last)
                loss_sum = loss_sum + jnp.where(use, ls, 0.0)
                loss_sum = loss_sum + jnp.where(do_b, aux_b, 0.0) * aux_w
                tok_sum = tok_sum + jnp.where(use, n, 0.0)

                # ---------- end-of-slot neighbor transport ----------
                y_in = lax.ppermute(y, PIPELINE_AXIS, fwd_perm)
                # the two permutes are data-independent; impose an order so
                # concurrent runtimes (XLA:CPU thunk executor) can't have
                # different ranks enter them in different order and deadlock
                y_in, dx = lax.optimization_barrier((y_in, dx))
                g_down = lax.ppermute(dx, PIPELINE_AXIS, bwd_perm)

                wf = inf % Kf
                cur = lax.dynamic_index_in_dim(stash, wf, 0, keepdims=False)
                stash = lax.dynamic_update_index_in_dim(
                    stash, jnp.where(inf >= 0, y_in, cur), wf, 0
                )
                wb = inb % Kb
                curg = lax.dynamic_index_in_dim(gstash, wb, 0, keepdims=False)
                gstash = lax.dynamic_update_index_in_dim(
                    gstash, jnp.where(inb >= 0, g_down, curg), wb, 0
                )
                return (stash, gstash, gl, ge, gh, loss_sum, tok_sum), None

            init = (
                jnp.zeros((Kf, *act.shape), act.dtype),
                jnp.zeros((Kb, *act.shape), act.dtype),
                jax.tree.map(jnp.zeros_like, layer_stack),
                jax.tree.map(jnp.zeros_like, embed_params),
                jax.tree.map(jnp.zeros_like, head_params),
                jnp.zeros((), jnp.float32),
                jnp.zeros((), jnp.float32),
            )
            (_, _, gl, ge, gh, loss_sum, tok_sum), _ = lax.scan(
                tick, init, (my_f, my_b, in_f, in_b)
            )
            all_axes = (DATA_AXIS, EXPERT_AXIS, PIPELINE_AXIS)
            loss_sum = lax.psum(loss_sum, all_axes)
            tok_sum = lax.psum(tok_sum, all_axes)
            # dp grad reduction is explicit here (dp is a manual axis):
            # layer grads live per-stage, embed/head grads on one stage only.
            # ep joins the psum ONLY for ep-replicated leaves — expert-
            # sharded leaves are distinct params per ep rank whose grads
            # arrive complete through the module's own collectives.
            flags = _ep_psum_flags(layer_specs, gl)
            gl = jax.tree.map(
                lambda g, rep: lax.psum(
                    g, (DATA_AXIS, EXPERT_AXIS) if rep else (DATA_AXIS,)),
                gl, flags)
            ge = jax.tree.map(lambda g: lax.psum(g, all_axes), ge)
            gh = jax.tree.map(lambda g: lax.psum(g, all_axes), gh)
            return (loss_sum, tok_sum), {LAYERS: gl, EMBED: ge, HEAD: gh}

        # dp/ep manual alongside pp — see make_pipelined_loss_fn's note
        lspecs = _layer_in_specs(layer_specs)
        shmap = _shard_map(
            f,
            mesh=mesh,
            in_specs=(lspecs, P(), P(), P(None, BATCH_AXES), P(None, BATCH_AXES),
                      *[P(None, BATCH_AXES)] * len(extras)),
            out_specs=((P(), P()), {LAYERS: lspecs, EMBED: P(), HEAD: P()}),
            axis_names=frozenset({DATA_AXIS, EXPERT_AXIS, PIPELINE_AXIS}),
            check_vma=False,
        )
        return shmap(params[LAYERS], params[EMBED], params[HEAD], ids_mb, labels_mb,
                     *extras_mb)

    return loss_and_grad


def _chunk_params(stack, v, chunk_rows: int):
    """Slice chunk ``v``'s rows out of the local ``[V*chunk_rows, ...]``
    stacked layer params (``v`` may be a traced scalar)."""
    return jax.tree.map(
        lambda leaf: lax.dynamic_slice_in_dim(leaf, v * chunk_rows, chunk_rows, 0),
        stack,
    )


def make_interleaved_1f1b_loss_and_grad_fn(
    embed_fn: EmbedFn,
    block_fn: BlockFn,
    head_loss_fn: HeadLossFn,
    num_microbatches: int,
    num_chunks: int,
    mesh: Optional[Mesh] = None,
    remat_block: bool = True,
    remat_policy: Optional[Callable] = None,
    act_spec: Optional[P] = None,
    block_aux: bool = False,
    layer_specs: Any = None,
    layer_mask=None,
):
    """Interleaved (virtual-stage) synchronous 1F1B — ``V = num_chunks``
    model chunks per pp rank (virtual stage ``s = v*P + r``), in one jit.

    Two improvements over :func:`make_1f1b_loss_and_grad_fn` (beyond-
    reference territory: the reference has no interleaving, SURVEY §2.10):

    1. **Chunk-granular ticks.** Each tick runs one chunk-forward and one
       chunk-backward (1/V of a stage each), so fill/drain overheads cost
       chunk-ticks.  Consecutive virtual stages sit on consecutive ranks,
       so the same single ring ppermute per tick carries every edge,
       including the rank ``P-1 → 0`` chunk wrap.
    2. **Phase-split scans.**  Tick-dependent (but rank-uniform) control
       flow is SPMD-safe — every mesh member shares the tick counter — so
       the schedule runs as THREE sequential ``lax.scan``s: a forward-only
       warmup (no garbage backward!), the mixed 1F1B middle, and a
       backward-only drain.  This removes the sync engine's chief tax
       (paying fwd+bwd on every fill/drain tick).  With fwd:bwd ≈ 1:2,
       total cost ≈ ``3·M·V + warmup·1 + drain·2`` chunk-units → bubble ≈
       ``(P-1)/(V·M + P-1)`` — *below* the reference's eager 1F1B bubble
       ``(P-1)/(M+P-1)`` for V ≥ 2, from a fully-SPMD program
       (``scheduler.bubble_fraction(..., "sync_interleaved")``).

    Stash slots are table-driven (offline interval coloring,
    ``scheduler.build_interleaved_sync_tables``) instead of modular
    arithmetic; peak stash is ``stash_size`` microbatch activations per
    rank (~2(P-1)·V·(V+1)/(2V) — interleaving's known activation premium).

    Composition (both restrictions lifted, VERDICT r4 #3): any ``M``
    (ragged microbatch counts are ghost-padded inside the table builder and
    masked out), and ``layer_mask`` marks padded rows from uneven
    virtual-stage spans (``partition.interleaved_layout_from_spans`` — the
    interleaved realization of ``pipeline_cuts``); the stacked layer count
    must still be ``P*V*per`` for a uniform chunk width ``per``.
    """
    mesh = mesh if mesh is not None else get_mesh()
    pp = mesh.shape[PIPELINE_AXIS]
    M, V = num_microbatches, num_chunks

    blk = block_fn
    if remat_block:
        blk = jax.checkpoint(block_fn, policy=remat_policy, prevent_cse=False)
    if layer_mask is None:
        stage_fn = _make_stage_fn(blk, None, block_aux, act_spec)
        mask_const = None
    else:
        stage_fn = _make_stage_fn(blk, "arg", block_aux, act_spec)
        mask_const = jnp.asarray(layer_mask, jnp.float32)
    n_real_layers = int(sum(layer_mask)) if layer_mask is not None else None

    if pp == 1:
        raise ValueError(
            "make_interleaved_1f1b_loss_and_grad_fn requires pp > 1; "
            "build_pipelined_model routes schedule='interleaved' at pp==1 "
            "to the plain 1F1B engine"
        )

    from neuronx_distributed_tpu.pipeline.scheduler import (
        build_interleaved_sync_tables,
    )
    import numpy as np

    tb = build_interleaved_sync_tables(M, pp, V)
    T, Ks, Kg = tb.num_slots, tb.stash_size, tb.gstash_size

    cols = {
        "fm": np.asarray(tb.fwd_mb, np.int32),
        "fc": np.asarray(tb.fwd_chunk, np.int32),
        "fs": np.asarray(tb.fwd_slot, np.int32),
        "bm": np.asarray(tb.bwd_mb, np.int32),
        "bc": np.asarray(tb.bwd_chunk, np.int32),
        "bs": np.asarray(tb.bwd_slot, np.int32),
        "gs": np.asarray(tb.gin_slot, np.int32),
        "inf": np.asarray(tb.in_fwd_slot, np.int32),
        "inb": np.asarray(tb.in_bwd_slot, np.int32),
    }
    any_b = (cols["bm"] >= 0).any(axis=0)  # [T]
    any_f = (cols["fm"] >= 0).any(axis=0)
    # phase boundaries: leading ticks with no backward anywhere; trailing
    # ticks with no forward anywhere (rank-uniform cut points)
    warm = int(np.argmax(any_b)) if any_b.any() else T
    drain_start = int(T - np.argmax(any_f[::-1])) if any_f.any() else 0
    assert warm <= drain_start

    fwd_perm = [(i, (i + 1) % pp) for i in range(pp)]
    bwd_perm = [(i, (i - 1) % pp) for i in range(pp)]

    def loss_and_grad(params, ids: jax.Array, labels: jax.Array, *extras):
        ids_mb = microbatch(ids, M, mesh)
        labels_mb = microbatch(labels, M, mesh)
        extras_mb = tuple(microbatch(e, M, mesh) for e in extras)
        L = jax.tree.leaves(params[LAYERS])[0].shape[0]
        if L % (pp * V) != 0:
            raise ValueError(
                f"stacked layer count {L} not divisible by pp*num_chunks "
                f"({pp}*{V})"
            )
        Lc = L // (pp * V)
        dpsz = mesh.shape[DATA_AXIS] * mesh.shape[EXPERT_AXIS]

        def f(layer_stack, embed_params, head_params, ids_mb, labels_mb, *extras_mb):
            rank = lax.axis_index(PIPELINE_AXIS)
            is_first = rank == 0
            is_last = rank == pp - 1
            tok_total = lax.psum(
                jnp.sum((labels_mb >= 0).astype(jnp.float32)), (DATA_AXIS, EXPERT_AXIS)
            )
            L_real = n_real_layers if n_real_layers is not None else L
            aux_w = tok_total / (L_real * M * dpsz)

            mb_shape = ids_mb.shape[1:]
            probe = jax.eval_shape(
                embed_fn, embed_params, jnp.zeros(mb_shape, ids_mb.dtype)
            )
            act = jax.ShapeDtypeStruct(probe.shape, probe.dtype)
            cact = _make_cact(act_spec)

            my = {k: jnp.take(jnp.asarray(a), rank, axis=0) for k, a in cols.items()}

            if mask_const is not None:
                local_mask = lax.dynamic_slice_in_dim(
                    mask_const, rank * (V * Lc), V * Lc, 0)

                def run_stage(stack, v, x, ex):
                    cm = lax.dynamic_slice_in_dim(local_mask, v * Lc, Lc, 0)
                    return stage_fn(_chunk_params(stack, v, Lc), x, ex, cm)
            else:
                def run_stage(stack, v, x, ex):
                    return stage_fn(_chunk_params(stack, v, Lc), x, ex)

            def masked_add(acc, delta, flag):
                return jax.tree.map(
                    lambda a, d: a + jnp.where(flag, d, jnp.zeros_like(d)), acc, delta
                )

            def fwd_part(stash, xs):
                """Compute this tick's chunk forward; returns (stash', y)."""
                mf, vf, fs = xs["fm"], xs["fc"], xs["fs"]
                do_f = mf >= 0
                vf_c = jnp.maximum(vf, 0)
                fs_c = jnp.maximum(fs, 0)
                ids_f = lax.dynamic_index_in_dim(
                    ids_mb, jnp.maximum(mf, 0), 0, keepdims=False)
                owns_embed = jnp.logical_and(is_first, vf_c == 0)
                x_emb = lax.cond(
                    owns_embed,
                    lambda ep: cact(embed_fn(ep, ids_f).astype(act.dtype)),
                    lambda ep: cact(jnp.zeros(act.shape, act.dtype)),
                    embed_params,
                )
                x_stash = cact(
                    lax.dynamic_index_in_dim(stash, fs_c, 0, keepdims=False))
                x_in = jnp.where(owns_embed, x_emb, x_stash)
                stash = lax.dynamic_update_index_in_dim(
                    stash, jnp.where(do_f, x_in, x_stash), fs_c, 0)
                ex_f = tuple(
                    lax.dynamic_index_in_dim(e, jnp.maximum(mf, 0), 0, keepdims=False)
                    for e in extras_mb
                )
                y, _ = run_stage(layer_stack, vf_c, x_in, ex_f)
                return stash, cact(y)

            def bwd_part(carry_grads, stash, gstash, xs):
                """Compute this tick's chunk backward; returns updated grad
                accumulators, the outgoing input-cotangent dx, and the tick's
                (loss, tok) contribution."""
                gl, ge, gh, loss_sum, tok_sum = carry_grads
                mb_, vb, bs, gs = xs["bm"], xs["bc"], xs["bs"], xs["gs"]
                do_b = mb_ >= 0
                vb_c = jnp.maximum(vb, 0)
                x_b = lax.dynamic_index_in_dim(
                    stash, jnp.maximum(bs, 0), 0, keepdims=False)
                g_in = lax.dynamic_index_in_dim(
                    gstash, jnp.maximum(gs, 0), 0, keepdims=False)
                lbl = lax.dynamic_index_in_dim(
                    labels_mb, jnp.maximum(mb_, 0), 0, keepdims=False)
                ids_b = lax.dynamic_index_in_dim(
                    ids_mb, jnp.maximum(mb_, 0), 0, keepdims=False)
                ex_b = tuple(
                    lax.dynamic_index_in_dim(e, jnp.maximum(mb_, 0), 0, keepdims=False)
                    for e in extras_mb
                )
                owns_head = jnp.logical_and(is_last, vb_c == V - 1)

                def objective(lp_full, hp, xx):
                    # same pp-uniform-cond argument as the V=1 engine; the
                    # predicate additionally varies by tick, which every
                    # member of an auto-axis collective channel shares.
                    yy, aux = run_stage(lp_full, vb_c, xx, ex_b)
                    ls, n = lax.cond(
                        owns_head,
                        lambda hp_, yy_: tuple(
                            o.astype(jnp.float32) for o in head_loss_fn(hp_, yy_, lbl)
                        ),
                        lambda hp_, yy_: (jnp.zeros((), jnp.float32),
                                          jnp.zeros((), jnp.float32)),
                        hp, yy,
                    )
                    dot = jnp.sum(yy.astype(jnp.float32) * g_in.astype(jnp.float32))
                    obj = jnp.where(owns_head, ls, dot) + aux_w * aux
                    return obj, (ls, n, aux.astype(jnp.float32))

                (_, (ls, n, aux_b)), vjp_fn = jax.vjp(
                    objective, layer_stack, head_params, x_b, has_aux=False)
                zero = jnp.zeros((), jnp.float32)
                dl, dh, dx = vjp_fn((jnp.ones((), jnp.float32), (zero, zero, zero)))
                dx = cact(dx)
                de = lax.cond(
                    jnp.logical_and(do_b, jnp.logical_and(is_first, vb_c == 0)),
                    lambda ep: jax.vjp(
                        lambda e: embed_fn(e, ids_b).astype(act.dtype), ep
                    )[1](dx)[0],
                    lambda ep: jax.tree.map(jnp.zeros_like, ep),
                    embed_params,
                )
                gl = masked_add(gl, dl, do_b)
                gh = masked_add(gh, dh, do_b)
                ge = jax.tree.map(jnp.add, ge, de)
                use = jnp.logical_and(do_b, owns_head)
                loss_sum = loss_sum + jnp.where(use, ls, 0.0)
                loss_sum = loss_sum + jnp.where(do_b, aux_b, 0.0) * aux_w
                tok_sum = tok_sum + jnp.where(use, n, 0.0)
                return (gl, ge, gh, loss_sum, tok_sum), dx

            def store_arrival(buf, incoming, slot):
                ok = slot >= 0
                sl = jnp.maximum(slot, 0)
                cur = lax.dynamic_index_in_dim(buf, sl, 0, keepdims=False)
                return lax.dynamic_update_index_in_dim(
                    buf, jnp.where(ok, incoming, cur), sl, 0)

            def tick_warm(carry, xs):
                stash, gstash, *grads = carry
                stash, y = fwd_part(stash, xs)
                y_in = lax.ppermute(y, PIPELINE_AXIS, fwd_perm)
                stash = store_arrival(stash, y_in, xs["inf"])
                return (stash, gstash, *grads), None

            def tick_full(carry, xs):
                stash, gstash, *grads = carry
                stash, y = fwd_part(stash, xs)
                grads, dx = bwd_part(tuple(grads), stash, gstash, xs)
                y_in = lax.ppermute(y, PIPELINE_AXIS, fwd_perm)
                y_in, dx = lax.optimization_barrier((y_in, dx))
                g_down = lax.ppermute(dx, PIPELINE_AXIS, bwd_perm)
                stash = store_arrival(stash, y_in, xs["inf"])
                gstash = store_arrival(gstash, g_down, xs["inb"])
                return (stash, gstash, *grads), None

            def tick_drain(carry, xs):
                stash, gstash, *grads = carry
                grads, dx = bwd_part(tuple(grads), stash, gstash, xs)
                g_down = lax.ppermute(dx, PIPELINE_AXIS, bwd_perm)
                gstash = store_arrival(gstash, g_down, xs["inb"])
                return (stash, gstash, *grads), None

            init = (
                jnp.zeros((Ks, *act.shape), act.dtype),
                jnp.zeros((Kg, *act.shape), act.dtype),
                jax.tree.map(jnp.zeros_like, layer_stack),
                jax.tree.map(jnp.zeros_like, embed_params),
                jax.tree.map(jnp.zeros_like, head_params),
                jnp.zeros((), jnp.float32),
                jnp.zeros((), jnp.float32),
            )
            carry = init
            for lo, hi, body in ((0, warm, tick_warm),
                                 (warm, drain_start, tick_full),
                                 (drain_start, T, tick_drain)):
                if lo == hi:
                    continue
                xs = {k: my[k][lo:hi] for k in my}
                carry, _ = lax.scan(body, carry, xs)
            _, _, gl, ge, gh, loss_sum, tok_sum = carry

            all_axes = (DATA_AXIS, EXPERT_AXIS, PIPELINE_AXIS)
            loss_sum = lax.psum(loss_sum, all_axes)
            tok_sum = lax.psum(tok_sum, all_axes)
            flags = _ep_psum_flags(layer_specs, gl)
            gl = jax.tree.map(
                lambda g, rep: lax.psum(
                    g, (DATA_AXIS, EXPERT_AXIS) if rep else (DATA_AXIS,)),
                gl, flags)
            ge = jax.tree.map(lambda g: lax.psum(g, all_axes), ge)
            gh = jax.tree.map(lambda g: lax.psum(g, all_axes), gh)
            return (loss_sum, tok_sum), {LAYERS: gl, EMBED: ge, HEAD: gh}

        lspecs = _layer_in_specs(layer_specs)
        shmap = _shard_map(
            f,
            mesh=mesh,
            in_specs=(lspecs, P(), P(), P(None, BATCH_AXES), P(None, BATCH_AXES),
                      *[P(None, BATCH_AXES)] * len(extras)),
            out_specs=((P(), P()), {LAYERS: lspecs, EMBED: P(), HEAD: P()}),
            axis_names=frozenset({DATA_AXIS, EXPERT_AXIS, PIPELINE_AXIS}),
            check_vma=False,
        )
        return shmap(params[LAYERS], params[EMBED], params[HEAD], ids_mb, labels_mb,
                     *extras_mb)

    return loss_and_grad


def make_interleaved_fwd_fn(
    embed_fn: EmbedFn,
    block_fn: BlockFn,
    num_microbatches: int,
    num_chunks: int,
    mesh: Optional[Mesh] = None,
    remat_block: bool = False,
    remat_policy: Optional[Callable] = None,
    act_spec: Optional[P] = None,
    block_aux: bool = False,
    layer_specs: Any = None,
    layer_mask=None,
):
    """Forward-only interleaved pipeline: ``fn(params, ids, *extras) ->
    (hidden [B, ...], aux_sum)`` with the last virtual stage's outputs
    regathered to the global batch.  Differentiable — serves as the loss
    oracle (autodiff backward) and the inference path of the interleaved
    engine.  ``layer_mask`` as in
    :func:`make_interleaved_1f1b_loss_and_grad_fn`."""
    mesh = mesh if mesh is not None else get_mesh()
    pp = mesh.shape[PIPELINE_AXIS]
    M, V = num_microbatches, num_chunks

    blk = block_fn
    if remat_block:
        blk = jax.checkpoint(block_fn, policy=remat_policy, prevent_cse=False)
    if layer_mask is None:
        stage_fn = _make_stage_fn(blk, None, block_aux, act_spec)
        mask_const = None
    else:
        stage_fn = _make_stage_fn(blk, "arg", block_aux, act_spec)
        mask_const = jnp.asarray(layer_mask, jnp.float32)

    from neuronx_distributed_tpu.pipeline.scheduler import (
        build_interleaved_fwd_tables,
    )
    import numpy as np

    tb = build_interleaved_fwd_tables(M, pp, V)
    T, Ks = tb.num_slots, tb.stash_size
    cols = {
        "fm": np.asarray(tb.fwd_mb, np.int32),
        "fc": np.asarray(tb.fwd_chunk, np.int32),
        "fs": np.asarray(tb.fwd_slot, np.int32),
        "inf": np.asarray(tb.in_fwd_slot, np.int32),
    }
    fwd_perm = [(i, (i + 1) % pp) for i in range(pp)]

    def fwd_fn(params, ids: jax.Array, *extras):
        ids_mb = microbatch(ids, M, mesh)
        extras_mb = tuple(microbatch(e, M, mesh) for e in extras)
        L = jax.tree.leaves(params[LAYERS])[0].shape[0]
        Lc = L // (pp * V)

        def f(layer_stack, embed_params, ids_mb, *extras_mb):
            rank = lax.axis_index(PIPELINE_AXIS)
            is_first = rank == 0
            is_last = rank == pp - 1
            mb_shape = ids_mb.shape[1:]
            probe = jax.eval_shape(
                embed_fn, embed_params, jnp.zeros(mb_shape, ids_mb.dtype))
            act = jax.ShapeDtypeStruct(probe.shape, probe.dtype)
            cact = _make_cact(act_spec)
            my = {k: jnp.take(jnp.asarray(a), rank, axis=0) for k, a in cols.items()}

            if mask_const is not None:
                local_mask = lax.dynamic_slice_in_dim(
                    mask_const, rank * (V * Lc), V * Lc, 0)

                def run_stage(stack, v, x, ex):
                    cm = lax.dynamic_slice_in_dim(local_mask, v * Lc, Lc, 0)
                    return stage_fn(_chunk_params(stack, v, Lc), x, ex, cm)
            else:
                def run_stage(stack, v, x, ex):
                    return stage_fn(_chunk_params(stack, v, Lc), x, ex)

            def tick(carry, xs):
                stash, outs, aux_sum = carry
                mf, vf, fs = xs["fm"], xs["fc"], xs["fs"]
                do_f = mf >= 0
                vf_c = jnp.maximum(vf, 0)
                fs_c = jnp.maximum(fs, 0)
                ids_f = lax.dynamic_index_in_dim(
                    ids_mb, jnp.maximum(mf, 0), 0, keepdims=False)
                owns_embed = jnp.logical_and(is_first, vf_c == 0)
                x_emb = lax.cond(
                    owns_embed,
                    lambda ep: cact(embed_fn(ep, ids_f).astype(act.dtype)),
                    lambda ep: cact(jnp.zeros(act.shape, act.dtype)),
                    embed_params,
                )
                x_stash = cact(
                    lax.dynamic_index_in_dim(stash, fs_c, 0, keepdims=False))
                x_in = jnp.where(owns_embed, x_emb, x_stash)
                ex_f = tuple(
                    lax.dynamic_index_in_dim(e, jnp.maximum(mf, 0), 0, keepdims=False)
                    for e in extras_mb
                )
                y, aux = run_stage(layer_stack, vf_c, x_in, ex_f)
                y = cact(y)
                aux_sum = aux_sum + jnp.where(do_f, aux, 0.0)
                # collect the LAST virtual stage's output for its microbatch
                emit = jnp.logical_and(
                    do_f, jnp.logical_and(is_last, vf_c == V - 1))
                m_c = jnp.maximum(mf, 0)
                cur = lax.dynamic_index_in_dim(outs, m_c, 0, keepdims=False)
                outs = lax.dynamic_update_index_in_dim(
                    outs, jnp.where(emit, y, cur), m_c, 0)
                y_in = lax.ppermute(y, PIPELINE_AXIS, fwd_perm)
                ok = xs["inf"] >= 0
                sl = jnp.maximum(xs["inf"], 0)
                curs = lax.dynamic_index_in_dim(stash, sl, 0, keepdims=False)
                stash = lax.dynamic_update_index_in_dim(
                    stash, jnp.where(ok, y_in, curs), sl, 0)
                return (stash, outs, aux_sum), None

            init = (
                jnp.zeros((Ks, *act.shape), act.dtype),
                jnp.zeros((M, *act.shape), act.dtype),
                jnp.zeros((), jnp.float32),
            )
            (_, outs, aux_sum), _ = lax.scan(tick, init, my)
            # every non-last rank contributed zeros to outs; aux must come
            # out replicated (out_spec P()), so reduce its manual axes too
            outs = lax.psum(outs, PIPELINE_AXIS)
            aux_sum = lax.psum(aux_sum, (DATA_AXIS, EXPERT_AXIS, PIPELINE_AXIS))
            return outs, aux_sum

        shmap = _shard_map(
            f,
            mesh=mesh,
            in_specs=(_layer_in_specs(layer_specs), P(), P(None, BATCH_AXES),
                      *[P(None, BATCH_AXES)] * len(extras)),
            out_specs=(P(None, BATCH_AXES), P()),
            axis_names=frozenset({DATA_AXIS, EXPERT_AXIS, PIPELINE_AXIS}),
            check_vma=False,
        )
        outs, aux_sum = shmap(params[LAYERS], params[EMBED], ids_mb, *extras_mb)
        hidden = outs.reshape(ids.shape[0], *outs.shape[2:])
        return hidden, aux_sum

    return fwd_fn


@dataclasses.dataclass
class PipelinedModel:
    """Facade over a pipeline-staged model (the PP analogue of the trainer's
    ``ParallelModel``; reference ``NxDPPModel``, ``pipeline/model.py:45``).

    ``loss_fn(params, ids, labels) -> (loss_sum, token_count)`` runs the full
    microbatch schedule (differentiable, fill-drain);
    ``loss_and_grad_fn(params, ids, labels) -> ((loss_sum, tok), grads)`` is
    the production train path (1F1B manual-backward when
    ``schedule="1f1b"``, autodiff of ``loss_fn`` otherwise);
    ``forward_fn(params, ids) -> logits`` is the fwd-only path."""

    params: Any
    param_specs: Any
    mesh: Mesh
    num_microbatches: int
    loss_fn: Callable
    forward_fn: Callable
    loss_and_grad_fn: Optional[Callable] = None
    schedule: str = "1f1b"
    # stack row of each real layer (identity when the layer count divides pp;
    # padded layout from partition.padded_layer_layout otherwise) — consumers
    # like checkpoint converters index the [L', ...] stack through this
    layer_rows: Optional[Tuple[int, ...]] = None
    # batch keys (beyond ids/labels) the schedule functions expect as extra
    # positional per-token arrays — e.g. ("positions", "segment_ids") for
    # packed pretraining; the trainer's pipelined step reads them from the
    # batch dict in this order
    extra_keys: Tuple[str, ...] = ()

    @property
    def param_shardings(self):
        return jax.tree.map(
            lambda s: NamedSharding(self.mesh, s),
            self.param_specs,
            is_leaf=lambda x: isinstance(x, P),
        )

    def num_parameters(self) -> int:
        return sum(int(x.size) for x in jax.tree.leaves(self.params))


def build_pipelined_model(
    embed_fn: EmbedFn,
    block_fn: BlockFn,
    head_loss_fn: HeadLossFn,
    head_fn: Callable[[Any, jax.Array], jax.Array],
    embed_init: Callable[[jax.Array], Any],
    block_init: Callable[[jax.Array], Any],
    head_init: Callable[[jax.Array], Any],
    num_layers: int,
    num_microbatches: int,
    mesh: Optional[Mesh] = None,
    remat_block: bool = True,
    remat_policy: Optional[Callable] = None,
    seed: int = 0,
    schedule: str = "1f1b",
    act_spec: Optional[P] = None,
    block_aux: bool = False,
    pipeline_cuts: Optional[Tuple[int, ...]] = None,
    extra_keys: Tuple[str, ...] = (),
    num_chunks: int = 1,
) -> PipelinedModel:
    """Initialize a pipelined model with stage parameters born sharded.

    ``*_init`` are flax ``Module.init`` thunks taking a PRNG key and
    returning a (possibly Partitioned-boxed) variable dict; block params are
    initialized per-layer under ``vmap`` into the stacked ``[L, ...]`` layout
    and placed pp-sharded (the GSPMD replacement for the reference's
    partition + sequential materialize-and-move,
    ``pipeline/model.py:1111-1125``)."""
    from flax import linen as nn

    mesh = mesh if mesh is not None else get_mesh()
    pp = mesh.shape[PIPELINE_AXIS]
    if schedule == "interleaved":
        if pp > 1:
            from neuronx_distributed_tpu.pipeline.partition import (
                interleaved_layout_from_spans,
                partition_uniform,
                spans_from_cuts,
            )

            S = pp * num_chunks
            if pipeline_cuts is not None:
                # cuts define VIRTUAL-stage boundaries under interleaving
                # (P*V spans in execution order) — the interleaved
                # realization of the reference's rebalancing tool
                spans = spans_from_cuts(pipeline_cuts, num_layers)
                if len(spans) != S:
                    raise ValueError(
                        f"interleaved pipeline_cuts must define "
                        f"pp*num_chunks = {S} virtual-stage spans "
                        f"({S - 1} cuts); got {len(spans)} spans"
                    )
            else:
                spans = partition_uniform(num_layers, S)
            padded_layers, row_of_layer, layer_mask = (
                interleaved_layout_from_spans(spans, pp, num_chunks))
            if all(m == 1 for m in layer_mask):
                layer_mask = None  # uniform divisible spans: no padding
        else:
            if pipeline_cuts is not None:
                raise ValueError(
                    "pipeline_cuts with pp == 1 has nothing to cut")
            padded_layers, row_of_layer, layer_mask = (
                num_layers, list(range(num_layers)), None)
    elif pipeline_cuts is not None:
        # explicit uneven stage partition (the reference's pipeline_cuts,
        # reference pipeline/partition.py:17-42).  The classic use: give the
        # LAST stage fewer layers so its extra head+loss work (which the
        # engines cond-gate onto it) stops being the per-tick critical path.
        from neuronx_distributed_tpu.pipeline.partition import (
            layout_from_spans,
            spans_from_cuts,
        )

        spans = spans_from_cuts(pipeline_cuts, num_layers)
        padded_layers, row_of_layer, layer_mask = layout_from_spans(spans, pp)
        if all(m == 1 for m in layer_mask):
            layer_mask = None  # cuts happen to be uniform: no padding needed
    elif num_layers % pp == 0:
        padded_layers, row_of_layer, layer_mask = num_layers, list(range(num_layers)), None
    else:
        # non-divisible: pad the stack with identity rows
        padded_layers, row_of_layer, layer_mask = padded_layer_layout(num_layers, pp)

    rng = jax.random.PRNGKey(seed)
    r_embed, r_head, r_layers = jax.random.split(rng, 3)

    def _params_of(tree):
        return tree["params"] if isinstance(tree, dict) and "params" in tree else tree

    def _specs_of(init, key):
        abs_tree = jax.eval_shape(init, key)
        return _params_of(nn.get_partition_spec(abs_tree))

    def _strip_manual_batch_axes(specs, keep_ep=False):
        """Drop dp (and, unless ``keep_ep``, ep) from param specs: the
        engine's shard_map makes those axes manual, so stage params must be
        replicated along the dropped ones.  ``keep_ep=True`` (the layer
        stack) RETAINS expert sharding: MoE expert-weight leaves carry
        ``ep`` in their partitioning metadata, the stacked specs become the
        shard_map in/out specs, and the block runs the module's manual-ep
        all-gather/psum-scatter path — real expert parallelism under PP
        (VERDICT r3 weak #3; dense models have no ep leaves and are
        unaffected)."""
        from neuronx_distributed_tpu.parallel.mesh import strip_axes_from_spec

        manual = frozenset({DATA_AXIS} if keep_ep else {DATA_AXIS, EXPERT_AXIS})
        return jax.tree.map(
            lambda s: strip_axes_from_spec(s, manual),
            specs, is_leaf=lambda x: isinstance(x, P),
        )

    embed_specs = _strip_manual_batch_axes(_specs_of(embed_init, r_embed))
    head_specs = _strip_manual_batch_axes(_specs_of(head_init, r_head))
    block_specs = _strip_manual_batch_axes(_specs_of(block_init, r_layers),
                                           keep_ep=True)
    layer_specs = stacked_layer_specs(block_specs)

    def _shardings(specs):
        return jax.tree.map(
            lambda s: NamedSharding(mesh, s), specs, is_leaf=lambda x: isinstance(x, P)
        )

    embed_params = jax.jit(
        lambda r: _params_of(nn.unbox(embed_init(r))), out_shardings=_shardings(embed_specs)
    )(r_embed)
    head_params = jax.jit(
        lambda r: _params_of(nn.unbox(head_init(r))), out_shardings=_shardings(head_specs)
    )(r_head)
    layer_keys = jax.random.split(r_layers, num_layers)
    rows = jnp.asarray(row_of_layer, jnp.int32)

    def _init_stack(ks):
        real = jax.vmap(lambda k: _params_of(nn.unbox(block_init(k))))(ks)
        if layer_mask is None and list(row_of_layer) == list(range(num_layers)):
            return real
        # scatter real layers into their (permuted and/or padded) rows;
        # padded rows stay zero
        return jax.tree.map(
            lambda leaf: jnp.zeros((padded_layers, *leaf.shape[1:]), leaf.dtype)
            .at[rows].set(leaf),
            real,
        )

    layer_params = jax.jit(_init_stack, out_shardings=_shardings(layer_specs))(layer_keys)

    params = {EMBED: embed_params, LAYERS: layer_params, HEAD: head_params}
    specs = {EMBED: embed_specs, LAYERS: layer_specs, HEAD: head_specs}

    if schedule == "interleaved" and pp > 1:
        # the contiguous-stage loss/forward paths would walk the permuted
        # stack in the wrong layer order; use the interleaved fwd timetable
        fwd_eval = make_interleaved_fwd_fn(
            embed_fn, block_fn, num_microbatches, num_chunks, mesh=mesh,
            remat_block=remat_block, remat_policy=remat_policy,
            act_spec=act_spec, block_aux=block_aux, layer_specs=layer_specs,
            layer_mask=layer_mask,
        )
        dpsz = mesh.shape[DATA_AXIS] * mesh.shape[EXPERT_AXIS]

        def loss_fn(params, ids, labels, *extras):
            hidden, aux_sum = fwd_eval(params, ids, *extras)
            ls, n = head_loss_fn(params[HEAD], hidden, labels)
            ls = ls.astype(jnp.float32)
            n = n.astype(jnp.float32)
            if block_aux:
                # mean over layers x microbatches x dp, scaled by tokens so
                # the caller's /tok recovers ce_mean + mean(aux) — the same
                # normalization as make_pipelined_loss_fn
                ls = ls + aux_sum / (num_layers * num_microbatches * dpsz) * n
            return ls, n

        def forward_fn(params, ids, *extras):
            hidden, _ = fwd_eval(params, ids, *extras)
            return head_fn(params[HEAD], hidden)

        loss_and_grad_fn = make_interleaved_1f1b_loss_and_grad_fn(
            embed_fn, block_fn, head_loss_fn, num_microbatches, num_chunks,
            mesh=mesh, remat_block=remat_block, remat_policy=remat_policy,
            act_spec=act_spec, block_aux=block_aux, layer_specs=layer_specs,
            layer_mask=layer_mask,
        )
        return _finalize_pipelined_model(
            params, specs, mesh, num_microbatches, loss_fn, forward_fn,
            loss_and_grad_fn, schedule, row_of_layer, extra_keys,
        )

    loss_fn = make_pipelined_loss_fn(
        embed_fn,
        block_fn,
        head_loss_fn,
        num_microbatches,
        mesh=mesh,
        remat_block=remat_block,
        remat_policy=remat_policy,
        layer_mask=layer_mask,
        block_aux=block_aux,
        act_spec=act_spec,
        layer_specs=layer_specs,
    )
    forward_fn = make_pipelined_forward_fn(
        embed_fn, block_fn, head_fn, num_microbatches, mesh=mesh,
        layer_mask=layer_mask, block_aux=block_aux, act_spec=act_spec,
        layer_specs=layer_specs,
    )
    if schedule == "1f1b" or (schedule == "interleaved" and pp == 1):
        loss_and_grad_fn = make_1f1b_loss_and_grad_fn(
            embed_fn,
            block_fn,
            head_loss_fn,
            num_microbatches,
            mesh=mesh,
            remat_block=remat_block,
            remat_policy=remat_policy,
            act_spec=act_spec,
            layer_mask=layer_mask,
            block_aux=block_aux,
            layer_specs=layer_specs,
        )
    elif schedule == "gpipe":
        def loss_and_grad_fn(params, ids, labels, *extras):
            (loss_sum, tok), grads = jax.value_and_grad(loss_fn, has_aux=True)(
                params, ids, labels, *extras
            )
            return (loss_sum, tok), grads
    else:
        raise ValueError(
            f"unknown pipeline schedule {schedule!r} (1f1b | gpipe | interleaved)"
        )
    return _finalize_pipelined_model(
        params, specs, mesh, num_microbatches, loss_fn, forward_fn,
        loss_and_grad_fn, schedule, row_of_layer, extra_keys,
    )


def _finalize_pipelined_model(
    params, specs, mesh, num_microbatches, loss_fn, forward_fn,
    loss_and_grad_fn, schedule, row_of_layer, extra_keys,
) -> PipelinedModel:
    if extra_keys:
        # fail at the call boundary with the key names, not mid-trace with
        # whatever unrelated error the missing operands trip first
        n_extra = len(extra_keys)

        def _check(got, fname):
            if got != n_extra:
                raise TypeError(
                    f"{fname} of this pipelined model takes {n_extra} extra "
                    f"per-token arrays ({', '.join(extra_keys)}) after its "
                    f"ids/labels arguments; got {got} — the trainer's "
                    "make_train_step supplies them from the batch dict"
                )

        _lf, _lg, _ff = loss_fn, loss_and_grad_fn, forward_fn

        def loss_fn(params, ids, labels, *ex):
            _check(len(ex), "loss_fn")
            return _lf(params, ids, labels, *ex)

        def loss_and_grad_fn(params, ids, labels, *ex):
            _check(len(ex), "loss_and_grad_fn")
            return _lg(params, ids, labels, *ex)

        def forward_fn(params, ids, *ex):
            _check(len(ex), "forward_fn")
            return _ff(params, ids, *ex)

    return PipelinedModel(
        params=params,
        param_specs=specs,
        mesh=mesh,
        num_microbatches=num_microbatches,
        loss_fn=loss_fn,
        forward_fn=forward_fn,
        loss_and_grad_fn=loss_and_grad_fn,
        schedule=schedule,
        layer_rows=tuple(row_of_layer),
        extra_keys=tuple(extra_keys),
    )


def make_pipelined_forward_fn(
    embed_fn: EmbedFn,
    block_fn: BlockFn,
    head_fn: Callable[[Any, jax.Array], jax.Array],
    num_microbatches: int,
    mesh: Optional[Mesh] = None,
    layer_mask=None,
    block_aux: bool = False,
    act_spec: Optional[P] = None,
    layer_specs: Any = None,
):
    """Forward-only pipeline (the reference's ``InferenceSchedule`` path,
    ``pipeline/model.py:run_eval``): returns ``fn(params, ids) -> outputs``
    with outputs stacked back to the global batch.

    Implementation: the hidden states exiting the last stage are collected
    per tick and broadcast from the last stage once at the end (one transfer,
    not one per microbatch), then the head runs under plain GSPMD.
    """
    mesh = mesh if mesh is not None else get_mesh()
    pp = mesh.shape[PIPELINE_AXIS]

    stage_fn = _make_stage_fn(block_fn, layer_mask, block_aux, act_spec)

    def forward_fn(params, ids: jax.Array, *extras):
        ids_mb = microbatch(ids, num_microbatches, mesh if pp > 1 else None)
        extras_mb = tuple(
            microbatch(e, num_microbatches, mesh if pp > 1 else None) for e in extras
        )
        M = num_microbatches

        if pp == 1:
            def one_mb(_, mb):
                i, *ex = mb
                x, _ = stage_fn(params[LAYERS], embed_fn(params[EMBED], i), tuple(ex))
                return None, head_fn(params[HEAD], x)

            _, outs = lax.scan(one_mb, None, (ids_mb, *extras_mb))
            return outs.reshape(ids.shape[0], *outs.shape[2:])

        T = M + pp - 1

        def f(layer_stack, embed_params, ids_mb, *extras_mb):
            rank = lax.axis_index(PIPELINE_AXIS)
            is_first = rank == 0
            is_last = rank == pp - 1
            mb_shape = ids_mb.shape[1:]
            probe = jax.eval_shape(embed_fn, embed_params, jnp.zeros(mb_shape, ids_mb.dtype))

            def tick(carry, t):
                buf, outs = carry
                feed_t = jnp.clip(t, 0, M - 1)
                ids_t = lax.dynamic_index_in_dim(ids_mb, feed_t, axis=0, keepdims=False)
                x_in = jnp.where(is_first, embed_fn(embed_params, ids_t), buf)
                my_t = jnp.clip(t - rank, 0, M - 1)
                ex_t = tuple(
                    lax.dynamic_index_in_dim(e, my_t, axis=0, keepdims=False)
                    for e in extras_mb
                )
                y, _ = stage_fn(layer_stack, x_in, ex_t)
                out_t = t - (pp - 1)
                write = jnp.where(jnp.logical_and(is_last, out_t >= 0), y, 0.0).astype(y.dtype)
                outs = lax.dynamic_update_index_in_dim(
                    outs, outs[jnp.clip(out_t, 0, M - 1)] + write, jnp.clip(out_t, 0, M - 1), axis=0
                )
                nxt = lax.ppermute(y, PIPELINE_AXIS, [(i, (i + 1) % pp) for i in range(pp)])
                return (nxt, outs), None

            init = (
                jnp.zeros(probe.shape, probe.dtype),
                jnp.zeros((M, *probe.shape), probe.dtype),
            )
            (_, outs), _ = lax.scan(tick, init, jnp.arange(T))
            # gather the last stage's buffer to every pp rank (single psum —
            # all other ranks contributed zeros)
            return lax.psum(outs, PIPELINE_AXIS)

        # dp/ep manual alongside pp — see make_pipelined_loss_fn's note
        shmap = _shard_map(
            f,
            mesh=mesh,
            in_specs=(_layer_in_specs(layer_specs), P(), P(None, BATCH_AXES),
                      *[P(None, BATCH_AXES)] * len(extras)),
            out_specs=P(None, BATCH_AXES),
            axis_names=frozenset({DATA_AXIS, EXPERT_AXIS, PIPELINE_AXIS}),
            check_vma=False,
        )
        hidden = shmap(params[LAYERS], params[EMBED], ids_mb, *extras_mb)
        logits = head_fn(params[HEAD], hidden.reshape(ids.shape[0], *hidden.shape[2:]))
        return logits

    return forward_fn
