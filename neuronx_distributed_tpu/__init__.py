"""neuronx_distributed_tpu — a TPU-native (JAX/XLA/pjit/pallas) distributed
training & inference framework with the capability surface of
``neuronx-distributed`` (AWS's Megatron-style model-parallelism library),
re-designed around ``jax.sharding.Mesh`` / GSPMD rather than ported.

Public API mirrors the reference's top-level exports
(``src/neuronx_distributed/__init__.py:1-7``).
"""

from neuronx_distributed_tpu.version import __version__
from neuronx_distributed_tpu.config import (
    ActivationCheckpointConfig,
    OptimizerConfig,
    PipelineConfig,
    TrainingConfig,
    training_config,
)
from neuronx_distributed_tpu.parallel.mesh import (
    MeshConfig,
    destroy_model_parallel,
    get_data_parallel_size,
    get_mesh,
    get_pipeline_parallel_size,
    get_tensor_parallel_size,
    initialize_model_parallel,
    model_parallel_is_initialized,
)

__all__ = [
    "__version__",
    "ActivationCheckpointConfig",
    "OptimizerConfig",
    "PipelineConfig",
    "TrainingConfig",
    "training_config",
    "MeshConfig",
    "initialize_model_parallel",
    "destroy_model_parallel",
    "model_parallel_is_initialized",
    "get_mesh",
    "get_tensor_parallel_size",
    "get_pipeline_parallel_size",
    "get_data_parallel_size",
]
