"""Paged KV-cache subsystem (ISSUE 5 tentpole).

Block-granular KV allocation with prefix reuse for the serving engine —
PagedAttention's memory model (Kwon et al., SOSP '23) and RadixAttention's
prefix sharing (Zheng et al., 2024) mapped onto static-shape JAX/pjit:

- :mod:`.allocator` — :class:`BlockAllocator`: host-side free-list page
  accounting with refcounted sharing, atomic allocation
  (:class:`PoolExhausted` takes nothing), copy-on-write, and no-leak /
  no-double-free invariant checks;
- :mod:`.prefix` — :class:`PrefixIndex`: a page-granular token trie mapping
  padded prompt prefixes to shared page chains (full-prompt hits carry the
  prefill logits, so repeated prompts skip prefill compute), with LRU
  eviction of refcount-0 chains;
- :mod:`.pool` — :class:`PagePool`: the preallocated
  ``[num_pages, page_size, kv_heads, head_dim]`` device arrays per layer
  (kv over tp, page axis a global unsharded pool) plus sizing arithmetic;
- :mod:`.transfer` — :func:`export_chain` / :func:`import_chain`: move a
  committed page chain between pools (fp and int8 layouts) with
  transactional failure semantics — the disaggregated fleet's KV
  migration and fleet-global prefix-cache primitive.

The serving integration lives one layer up:
``serving.paged.PagedKVManager`` glues these onto the engine's slot table,
``trace.ParallelInferenceModel`` compiles the paged phase programs
(``decode_pages`` / ``write_page`` / ``copy_page``), and ``models.llama``
carries the block-table gather/scatter decode path.
"""

from neuronx_distributed_tpu.kvcache.allocator import (
    NULL_PAGE,
    BlockAllocator,
    PoolExhausted,
)
from neuronx_distributed_tpu.kvcache.pool import (
    GATHER_BYTES_TOTAL,
    PagePool,
    init_page_pool_caches,
)
from neuronx_distributed_tpu.kvcache.prefix import (
    PAD,
    PrefixIndex,
    is_padding_key,
    page_keys,
)
from neuronx_distributed_tpu.kvcache.transfer import (
    PAGES_EXPORTED_TOTAL,
    PAGES_IMPORTED_TOTAL,
    ChainExport,
    TransferError,
    export_chain,
    import_chain,
)

__all__ = [
    "BlockAllocator",
    "ChainExport",
    "GATHER_BYTES_TOTAL",
    "NULL_PAGE",
    "PAD",
    "PAGES_EXPORTED_TOTAL",
    "PAGES_IMPORTED_TOTAL",
    "PagePool",
    "PoolExhausted",
    "PrefixIndex",
    "TransferError",
    "export_chain",
    "import_chain",
    "init_page_pool_caches",
    "is_padding_key",
    "page_keys",
]
