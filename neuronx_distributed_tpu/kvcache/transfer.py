"""KV page-chain transfer between page pools (disaggregated serving).

The primitive that makes prefill/decode disaggregation work (DistServe,
Zhong et al. 2024; Splitwise, Patel et al. 2024; Mooncake's KVCache-centric
transfer): serialize a committed prompt page chain out of one replica's
pool and admit it into a sibling's pool as a prefix chain that is
TOKEN-IDENTICAL to what local prefill would have produced.

Two halves, mirroring the pool's host/device split:

- :func:`export_chain` — gather the chain's real pages off the device into
  a host-side :class:`ChainExport`.  Works for both pool layouts (the fp
  ``(k, v)`` pair and the int8 six-tuple) by exploiting the pool's one
  structural invariant: EVERY leaf has a leading ``num_pages`` axis, so
  one fancy-index gather per leaf moves a page's KV and its per-page
  quantization params alike.  Padding pages (NULL-backed) carry no
  content and ship as structure only.
- :func:`import_chain` — admit an export into a destination pool: reuse
  whatever leading chain the destination's :class:`~.prefix.PrefixIndex`
  already holds, atomically allocate pages for the rest, scatter the
  exported rows in (one batched ``.at[pages].set(rows)`` per leaf), and
  register the full chain in the destination index with the export's
  terminal payload.  The destination ends in exactly the state a local
  prefill + ``finish_insert`` would have left: the index owns one
  reference per page.

Failure semantics match the allocator's atomic-alloc discipline (the PR-5
chaos contract): the ``kvcache/page_import`` fault point sits between
allocation and commit, and ANY failure releases every page and reference
taken before re-raising — a killed migration leaks nothing on either side.

Serialization is host numpy — the export is process-portable by
construction (a cross-host fleet would frame ``ChainExport`` over its
transport; in-process fleets hand it over directly).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, List, Optional, Sequence, Tuple

import numpy as np

from neuronx_distributed_tpu.kvcache.allocator import NULL_PAGE, BlockAllocator
from neuronx_distributed_tpu.kvcache.prefix import (
    PageKey,
    PrefixIndex,
    prefix_fingerprints,
)
from neuronx_distributed_tpu.resilience.faults import fault_point
from neuronx_distributed_tpu.utils.logger import get_logger

logger = get_logger(__name__)

PAGES_EXPORTED_TOTAL = "kvcache/pages_exported_total"
PAGES_IMPORTED_TOTAL = "kvcache/pages_imported_total"


class TransferError(RuntimeError):
    """The export cannot be admitted into this pool — incompatible layouts
    (page size, layer count, quantization, head geometry) or a corrupt
    chain.  Raised BEFORE any destination state changes."""


@dataclass
class ChainExport:
    """One committed page chain, serialized to the host.

    ``keys``/``pages`` cover the FULL chain root-down (padding pages ride
    as NULL, same as a block table); ``leaves`` holds, per layer, one host
    array per pool leaf with the chain's real (non-NULL) pages stacked
    along the leading axis in chain order — ``leaves[l][j][i]`` is layer
    ``l``, leaf ``j``, ``i``-th real page of the chain.
    """

    keys: List[PageKey]
    pages: List[int]                 # SOURCE page ids (diagnostic only)
    layout: str                      # "fp" | "int8"
    page_size: int
    num_layers: int
    leaves: List[Tuple[np.ndarray, ...]]
    payload: Optional[np.ndarray] = None
    fingerprint: int = 0
    source: Any = None               # exporting replica id (diagnostic)
    meta: dict = field(default_factory=dict)

    @property
    def n_pages(self) -> int:
        """Real (non-NULL) pages in the chain — what import must allocate
        on a cold destination."""
        return sum(1 for p in self.pages if p != NULL_PAGE)

    @property
    def nbytes(self) -> int:
        """Serialized KV payload size (leaves + terminal payload) — the
        migration span's byte attribute."""
        n = sum(leaf.nbytes for layer in self.leaves for leaf in layer)
        if self.payload is not None:
            n += self.payload.nbytes
        return n


def _layout_of(caches: Sequence[Tuple]) -> str:
    if not caches:
        raise TransferError("empty page pool (no layers)")
    width = len(caches[0])
    if width == 2:
        return "fp"
    if width == 6:
        return "int8"
    raise TransferError(f"unknown pool layout: {width} leaves per layer")


def export_chain(caches: Sequence[Tuple], keys: Sequence[PageKey],
                 pages: Sequence[int], page_size: int,
                 payload: Any = None, registry: Any = None,
                 source: Any = None) -> ChainExport:
    """Serialize the chain ``(keys, pages)`` out of a live page pool.

    The caller must hold the pages live for the duration of the call (a
    slot's references or the index's own — both the migration and the
    fleet-prefix paths do).  ``payload`` is the chain's terminal prefill
    logits (device or host); it ships as host numpy so the importer's
    full-hit path can hand it straight to the engine.
    """
    if len(keys) != len(pages):
        raise TransferError(f"{len(keys)} keys vs {len(pages)} pages")
    layout = _layout_of(caches)
    real = np.asarray([int(p) for p in pages if p != NULL_PAGE], np.int32)
    leaves: List[Tuple[np.ndarray, ...]] = []
    for layer in caches:
        leaves.append(tuple(np.asarray(leaf[real]) for leaf in layer))
    fps = prefix_fingerprints(list(keys))
    export = ChainExport(
        keys=list(keys), pages=[int(p) for p in pages], layout=layout,
        page_size=page_size, num_layers=len(caches), leaves=leaves,
        payload=None if payload is None else np.asarray(payload),
        fingerprint=fps[-1] if fps else 0, source=source)
    if registry is not None:
        registry.counter(PAGES_EXPORTED_TOTAL).inc(export.n_pages)
    return export


def _check_compat(caches: Sequence[Tuple], export: ChainExport) -> None:
    """Role-compatible pools may differ in CAPACITY (page count) but never
    in page geometry — a row scattered into the wrong shape would be
    silent corruption, so every mismatch is a loud :class:`TransferError`
    before any destination state changes."""
    if _layout_of(caches) != export.layout:
        raise TransferError(
            f"layout mismatch: pool is {_layout_of(caches)!r}, "
            f"export is {export.layout!r}")
    if len(caches) != export.num_layers:
        raise TransferError(
            f"layer mismatch: pool has {len(caches)}, "
            f"export has {export.num_layers}")
    for l, (layer, rows) in enumerate(zip(caches, export.leaves)):
        for leaf, row in zip(layer, rows):
            if tuple(leaf.shape[1:]) != tuple(row.shape[1:]):
                raise TransferError(
                    f"page geometry mismatch at layer {l}: pool leaf "
                    f"{tuple(leaf.shape[1:])} vs export row "
                    f"{tuple(row.shape[1:])}")
            if str(leaf.dtype) != str(row.dtype):
                raise TransferError(
                    f"dtype mismatch at layer {l}: pool {leaf.dtype} vs "
                    f"export {row.dtype}")


def import_chain(caches, index: PrefixIndex, export: ChainExport,
                 registry: Any = None):
    """Admit ``export`` into a destination pool as a registered prefix
    chain.  Returns the updated caches pytree (functional — the caller
    swaps its live pytree, same convention as the compiled phase fns).

    Transactional: reuses the destination's already-cached leading chain,
    atomically allocates the missing tail (LRU-evicting index-only chains
    when the free list is short), scatters the exported rows, registers
    the full chain in ``index``, and on ANY failure — including the
    ``kvcache/page_import`` chaos fault point between allocation and
    commit — releases every page and reference taken before re-raising.
    On success the index owns exactly one reference per real page, the
    same terminal state as a local prefill's ``finish_insert``.
    """
    import jax.numpy as jnp

    _check_compat(caches, export)
    alloc: BlockAllocator = index.alloc
    matched, _ = index.lookup(export.keys)   # refs we now hold
    taken = [p for p in matched if p != NULL_PAGE]
    fresh: List[int] = []
    try:
        # the tail the destination is missing; padding keys ride NULL
        tail = list(range(len(matched), len(export.keys)))
        need = [i for i in tail if export.pages[i] != NULL_PAGE]
        short = len(need) - alloc.free_count
        if short > 0:
            index.evict(short)
        fresh = alloc.alloc(len(need))
        taken += fresh
        # chaos hook: a kill between allocation and commit must leak
        # nothing on either side (tests/test_disagg.py)
        fault_point("kvcache/page_import", pages=len(need),
                    fingerprint=export.fingerprint)
        if need:
            # chain position -> row index in the export's stacked leaves
            row_of = {i: j for j, i in enumerate(
                i for i, p in enumerate(export.pages) if p != NULL_PAGE)}
            sel = np.asarray([row_of[i] for i in need], np.int64)
            dst = jnp.asarray(np.asarray(fresh, np.int32))
            new_caches = []
            for layer, rows in zip(caches, export.leaves):
                new_caches.append(tuple(
                    leaf.at[dst].set(jnp.asarray(row[sel]))
                    for leaf, row in zip(layer, rows)))
            caches = new_caches
        full = list(matched)
        it = iter(fresh)
        for i in tail:
            full.append(NULL_PAGE if export.pages[i] == NULL_PAGE
                        else next(it))
        index.insert(export.keys, full, payload=export.payload)
    except BaseException:
        for p in taken:
            alloc.free(p)
        raise
    # the index retained its own references; drop ours (lookup refs on the
    # matched prefix, allocation refs on the fresh tail)
    alloc.free_tail(taken)
    if registry is not None:
        registry.counter(PAGES_IMPORTED_TOTAL).inc(len(fresh))
    return caches
