"""Host-side page accounting for the paged KV cache.

The :class:`BlockAllocator` owns the *bookkeeping* of the device page pool
(:mod:`.pool`): a free list plus a refcount per allocated page.  It is pure
Python with no jax imports, so every allocation policy property (atomic
allocation, no leak, no double free, copy-on-write semantics) is testable
without compiling anything — the same layering as the serving
``SlotScheduler``.

Page ``0`` is the reserved NULL page: block-table entries that back nothing
(left-padding pages, not-yet-written decode pages) all point at it.  Its
device content is never written, it is never allocated, and ``retain`` /
``free`` on it are no-ops — so callers can treat a block-table row uniformly
without special-casing holes.

Allocation is ATOMIC: ``alloc(n)`` either returns ``n`` pages or raises
:class:`PoolExhausted` having taken nothing.  A partial grant would be a
leak factory — the caller's cleanup path would have to know how far the
allocator got.

Sharing is by refcount: a prefix-cache hit ``retain``\\ s the shared pages,
and ``free`` only returns a page to the free list when the last reference
drops.  ``cow`` implements copy-on-write at the accounting level: writing a
page you share requires either exclusivity (refcount 1 — write in place) or
a fresh page (the caller device-copies the content and writes the copy).
"""

from __future__ import annotations

from typing import Any, Dict, Iterable, List, Optional, Tuple

from neuronx_distributed_tpu.utils.logger import get_logger

logger = get_logger(__name__)

# reserved zero page: block-table entries with nothing behind them point here
NULL_PAGE = 0

COW_COPIES_TOTAL = "kvcache/cow_copies_total"


class PoolExhausted(RuntimeError):
    """The page pool cannot satisfy the allocation right now — a
    *transient* condition (pages free as requests terminate or the prefix
    cache evicts), the kv-page analogue of the serving
    ``BackpressureError``: retry after load drains.  The failed ``alloc``
    took nothing (never a partial allocation)."""


class BlockAllocator:
    """Free-list page allocator with refcounted sharing.

    ``num_pages`` is the device pool's total page count *including* the
    reserved NULL page, so :attr:`capacity` (= ``num_pages - 1``) is what is
    actually allocatable.  ``registry`` (an ``obs.MetricRegistry``) receives
    ``kvcache/cow_copies_total`` when given.
    """

    def __init__(self, num_pages: int, registry: Any = None):
        if num_pages < 2:
            raise ValueError(
                f"num_pages must be >= 2 (page 0 is the reserved NULL page), "
                f"got {num_pages}")
        self.num_pages = num_pages
        self.registry = registry
        # pop() hands out low ids first — deterministic, test-friendly order
        self._free: List[int] = list(range(num_pages - 1, NULL_PAGE, -1))
        self._refs: Dict[int, int] = {}
        # bumped on every refcount mutation — lets PrefixIndex memoize its
        # trie-wide evictable count between mutations (the steady decode
        # path mutates nothing, so per-step gauge export stays O(1))
        self.version = 0
        if registry is not None:
            registry.counter(COW_COPIES_TOTAL)

    # -- introspection -----------------------------------------------------

    @property
    def capacity(self) -> int:
        """Allocatable pages (the NULL page excluded)."""
        return self.num_pages - 1

    @property
    def free_count(self) -> int:
        return len(self._free)

    @property
    def in_use(self) -> int:
        return len(self._refs)

    def refcount(self, page: int) -> int:
        """Live references on ``page`` (0 = free/unknown).  The NULL page has
        no refcount — asking for one is a caller bug."""
        if page == NULL_PAGE:
            raise ValueError("the NULL page is not refcounted")
        return self._refs.get(page, 0)

    # -- lifecycle ---------------------------------------------------------

    def alloc(self, n: int) -> List[int]:
        """Take ``n`` pages (each with refcount 1) or raise
        :class:`PoolExhausted` having taken NOTHING."""
        if n < 0:
            raise ValueError(f"cannot allocate {n} pages")
        if n > len(self._free):
            raise PoolExhausted(
                f"need {n} KV pages, {len(self._free)} free "
                f"(capacity {self.capacity}); retry after requests drain or "
                "the prefix cache evicts")
        pages = [self._free.pop() for _ in range(n)]
        for p in pages:
            self._refs[p] = 1
        self.version += 1
        return pages

    def retain(self, page: int) -> None:
        """Add a reference to an allocated page (prefix-cache sharing).
        No-op on the NULL page."""
        if page == NULL_PAGE:
            return
        if page not in self._refs:
            raise ValueError(f"retain of unallocated page {page}")
        self._refs[page] += 1
        self.version += 1

    def free(self, page: int) -> None:
        """Drop one reference; the page returns to the free list when the
        last reference drops.  No-op on the NULL page; freeing an
        unallocated page is a double free and raises."""
        if page == NULL_PAGE:
            return
        rc = self._refs.get(page)
        if rc is None:
            raise ValueError(f"double free / free of unallocated page {page}")
        if rc == 1:
            del self._refs[page]
            self._free.append(page)
        else:
            self._refs[page] = rc - 1
        self.version += 1

    def free_tail(self, pages: "Iterable[int]") -> int:
        """Release a whole TAIL of page references in one call — the
        speculative-decoding rollback path: a rejected draft tail (or a
        terminal slot's worst-case overshoot reservation) rolls back by
        refcount alone, no device copy.  NULL pages in the list are skipped
        (block-table holes ride through uniformly).  Returns how many pages
        actually returned to the free list (shared prefix pages only
        decref).  Each drop is the same accounting as :meth:`free`, so the
        no-leak/no-double-free invariants hold unchanged."""
        freed = 0
        for p in pages:
            if p == NULL_PAGE:
                continue
            exclusive = self._refs.get(p) == 1
            self.free(p)
            freed += int(exclusive)
        return freed

    def cow(self, page: int) -> Tuple[int, bool]:
        """Copy-on-write: make ``page`` writable for a caller holding one
        reference.  Exclusive (refcount 1) pages are returned as-is
        (``(page, False)``); shared pages release the caller's reference and
        allocate a fresh exclusive page (``(new_page, True)`` — the caller
        must device-copy the old content before writing).  Atomic: on
        :class:`PoolExhausted` the original reference is untouched."""
        if page == NULL_PAGE:
            raise ValueError("the NULL page is never writable")
        rc = self._refs.get(page)
        if rc is None:
            raise ValueError(f"cow of unallocated page {page}")
        if rc == 1:
            return page, False
        [new] = self.alloc(1)  # may raise PoolExhausted; nothing changed yet
        self._refs[page] = rc - 1
        if self.registry is not None:
            self.registry.counter(COW_COPIES_TOTAL).inc()
        return new, True

    # -- invariants --------------------------------------------------------

    def assert_invariants(self) -> None:
        """No page both free and allocated, no duplicates, no NULL page in
        either set, every refcount >= 1, free + in-use == capacity.
        O(pages) — cheap enough to run after every op in tests."""
        free = set(self._free)
        assert len(free) == len(self._free), "duplicate pages in free list"
        assert NULL_PAGE not in free and NULL_PAGE not in self._refs, (
            "the NULL page entered circulation")
        assert not (free & set(self._refs)), (
            f"pages both free and allocated: {sorted(free & set(self._refs))}")
        for p, rc in self._refs.items():
            assert 0 < p < self.num_pages, f"page id {p} out of range"
            assert rc >= 1, f"page {p} allocated with refcount {rc}"
        for p in free:
            assert 0 < p < self.num_pages, f"free page id {p} out of range"
        assert len(free) + len(self._refs) == self.capacity, (
            f"page leak: {len(free)} free + {len(self._refs)} in use "
            f"!= capacity {self.capacity}")
