"""Int8 page quantization for the paged KV cache (KIVI, Liu et al. 2024:
KV tensors tolerate low-bit quantization with bounded logit drift).

A quantized page pool stores each ``[page, NKV, D]`` page as int8 plus ONE
fp32 ``(scale, zero)`` pair per page (asymmetric affine: ``x ≈ (q + 128) *
scale + zero``), halving the HBM a page costs versus bf16 — the pool holds
~2x the pages at a fixed budget, and HBM (not compute) is what caps serving
concurrency (PR 5's measured result).  The quantization granularity is the
PAGE — the same unit the allocator refcounts — so quantize-on-write happens
exactly where page writes already happen (``write_page`` prefill writes,
the single-token decode scatter) and dequantize-in-the-gather reproduces
the same ``[B, T]`` view the band-mask attention core consumes, leaving
the attention math untouched.

Error model: an asymmetric 8-bit page has max absolute error
``(max - min) / 255 / 2`` — :func:`quant_error_bound` is the per-page bound
the parity-tolerance tests assert against (exact equality is the WRONG
test for a lossy cache; a bounded-drift regression threshold is the right
one).  Two exactness cases fall out of the affine form: an all-constant
page round-trips exactly (``scale == 0``, ``zero`` carries the value — the
zero decode tail never drifts), and so does any two-valued page.

Pure jnp helpers, shared by the model's scatter/gather path and the
serving wrapper's page-write programs; no engine state lives here.
"""

from __future__ import annotations

import jax.numpy as jnp

# registry counter: pages written through a quantize-on-write path
QUANT_PAGES_TOTAL = "kvcache/quant_pages_total"

# int8 codes span [-128, 127]; the affine form uses the unsigned view
_LEVELS = 255.0
_OFFSET = 128.0


def quantize_page(x):
    """Quantize pages over their trailing ``[page, NKV, D]`` axes.

    ``x`` is ``[..., page, NKV, D]`` float; returns ``(q int8, scale fp32,
    zero fp32)`` with ``scale``/``zero`` shaped like the leading axes.
    Asymmetric affine per page: ``zero = min(x)``, ``scale = (max - min) /
    255``; an all-constant page gets ``scale == 0`` and round-trips
    exactly through ``zero``."""
    xf = x.astype(jnp.float32)
    mn = jnp.min(xf, axis=(-3, -2, -1))
    mx = jnp.max(xf, axis=(-3, -2, -1))
    scale = (mx - mn) / _LEVELS
    safe = jnp.where(scale > 0.0, scale, 1.0)
    q = jnp.round((xf - mn[..., None, None, None]) / safe[..., None, None, None])
    q = jnp.clip(q, 0.0, _LEVELS) - _OFFSET
    return q.astype(jnp.int8), scale, mn


def dequantize_page(q, scale, zero, dtype=jnp.float32):
    """Invert :func:`quantize_page`: ``q`` is ``[..., page, NKV, D]`` int8,
    ``scale``/``zero`` its leading-axes fp32 params."""
    xf = (q.astype(jnp.float32) + _OFFSET) * scale[..., None, None, None] \
        + zero[..., None, None, None]
    return xf.astype(dtype)


def quant_error_bound(x) -> float:
    """Max absolute round-trip error the affine page code permits for the
    given page content: half a quantization step, ``(max - min) / 255 / 2``
    (plus fp32 rounding slack).  The parity-tolerance tests assert the
    observed drift under this bound instead of demanding exact equality."""
    import numpy as np

    xf = np.asarray(x, np.float32)
    return float((xf.max() - xf.min()) / _LEVELS / 2.0 + 1e-6)


def page_layer_bytes(page_size: int, num_kv_heads: int, head_dim: int,
                     quant: str | None, dtype) -> int:
    """HBM bytes ONE page costs for ONE layer's k+v under the given layout:
    the fp pool pays ``2 * page * NKV * D * itemsize``; the int8 pool pays
    1 byte per element plus four fp32 page params (k/v scale + zero) — the
    honest per-page accounting :meth:`PagePool.pages_for_budget` sizes
    with."""
    elems = page_size * num_kv_heads * head_dim
    if quant is None:
        return 2 * elems * jnp.dtype(dtype).itemsize
    if quant != "int8":
        raise ValueError(f"unknown KV quantization {quant!r} "
                         "(supported: 'int8')")
    return 2 * elems * 1 + 4 * 4  # int8 payload + (ks, kz, vs, vz) fp32
