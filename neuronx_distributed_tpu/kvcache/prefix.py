"""Prefix index: a token-hash trie mapping prompt prefixes to shared page
chains (RadixAttention, Zheng et al. 2024, on the static-shape page pool).

A serving fleet's prompts repeat — system prompts, few-shot preambles,
multi-turn histories.  The prefix index deduplicates their KV at PAGE
granularity: each trie node is one page worth of tokens (the *page key*,
:func:`page_keys`) and owns the physical page holding that page's K/V.  Two
prompts whose padded rows agree on a page-aligned prefix share the physical
pages of that prefix (refcounted in the :class:`~.allocator.BlockAllocator`),
and an exact full-prompt hit additionally carries the prefill's last-position
logits as the terminal payload, so a repeated prompt skips prefill compute
entirely.

Why keys are built from the PADDED row: the engine left-pads prompts to the
compiled context width, and a token's KV depends on its position *within the
padded row* (RoPE phases come from the validity prefix).  Padding slots are
encoded as :data:`PAD`, so two rows share a page key only when both the
tokens and the padding layout match — which is exactly the condition under
which the cached KV page is bit-identical to what prefill would recompute.
Pages that are ALL padding carry no information (their keys are all
:data:`PAD`, their content is masked out of every attention) and map to the
allocator's NULL page — cacheable structure, zero pages spent.

Chains are immutable once written: prompts occupy page-aligned context
region ``[0, C)`` and decode writes start at ``C``, so a shared prompt page
is never mutated and sharing needs no copy-on-write on this path (the
allocator still provides ``cow`` for callers that share mid-page state).

Eviction is LRU over refcount-0 chains: a leaf whose page only the index
still references (allocator refcount 1) is reclaimable; evicting leaves
bottom-up keeps every active request's chain intact (a pinned descendant
implies pinned ancestors — requests reference whole prefixes).

Pure host-side (no jax) — the trie, refcount and LRU properties are tested
without compiling anything.
"""

from __future__ import annotations

import hashlib
import struct
from typing import Any, Iterator, List, Optional, Sequence, Set, Tuple

from neuronx_distributed_tpu.kvcache.allocator import NULL_PAGE, BlockAllocator

# page-key code for a left-padding slot (never a valid token id)
PAD = -1

# leading marker of a salted (per-adapter) page key — distinct from PAD and
# from any valid token id, so a salted key can never collide with a plain one
SALT_MARK = -2

EVICTIONS_TOTAL = "kvcache/evictions_total"

PageKey = Tuple[int, ...]


def page_keys(ids_row: Sequence[int], valid_row: Sequence[int],
              page_size: int, salt: int = 0) -> List[PageKey]:
    """Page keys for one padded prompt row: per page, the tuple of token ids
    with padding slots replaced by :data:`PAD`.  ``ids_row`` / ``valid_row``
    are the row's ``[C]`` padded ids and 0/1 validity; ``C`` must divide by
    ``page_size``.

    ``salt`` namespaces the keys (the tenancy subsystem salts with the
    request's LoRA ``adapter_id``): a cached KV page's content depends on
    the adapter that prefilled it (the v projection carries the adapter
    delta), so two requests may share a prefix page only when their tokens,
    padding layout AND adapter all agree.  Non-padding keys grow a leading
    ``(SALT_MARK, salt)`` pair; all-padding pages stay the plain all-PAD
    key — their content is masked out of every attention, so the NULL page
    backs them for free regardless of adapter.  ``salt == 0`` (the
    no-adapter default) keeps the historical key format bit-for-bit, so
    existing tries and fleet fingerprints are unchanged."""
    n = len(ids_row)
    if n % page_size != 0:
        raise ValueError(
            f"row length {n} is not a multiple of page_size {page_size}")
    keys = []
    for p in range(n // page_size):
        lo = p * page_size
        key = tuple(
            int(ids_row[lo + i]) if valid_row[lo + i] else PAD
            for i in range(page_size))
        if salt and not is_padding_key(key):
            key = (SALT_MARK, int(salt)) + key
        keys.append(key)
    return keys


def is_padding_key(key: PageKey) -> bool:
    """True when the page holds no real token (all left-padding) — such
    pages map to the NULL page and cost nothing."""
    return all(t == PAD for t in key)


# -- chain fingerprints (fleet router shadow index) --------------------------
#
# A fleet router steering by prefix affinity needs to know which replica's
# PrefixIndex likely holds a prompt's leading page chain WITHOUT holding the
# chain itself (the router is a front door over N replicas, possibly across
# process boundaries).  A *chain fingerprint* is a stable 64-bit rolling hash
# of a page-key chain: fp_0 = ROOT_FINGERPRINT, fp_n = H(fp_{n-1}, key_n).
# blake2b (not Python ``hash``) so fingerprints agree across processes and
# across runs — the contract between a live index's
# :meth:`PrefixIndex.chain_fingerprints` export and the router-side shadow.

ROOT_FINGERPRINT = 0


def chain_fingerprint(parent_fp: int, key: PageKey) -> int:
    """Extend a chain fingerprint by one page key (rolling, order-sensitive:
    the fingerprint of a chain depends on every key before it)."""
    h = hashlib.blake2b(digest_size=8)
    h.update(int(parent_fp).to_bytes(8, "little"))
    h.update(struct.pack(f"<{len(key)}q", *key))
    return int.from_bytes(h.digest(), "little")


def prefix_fingerprints(keys: Sequence[PageKey]) -> List[int]:
    """Fingerprint of every leading chain of ``keys``: element ``i`` is the
    fingerprint of ``keys[:i+1]``.  The router hashes a prompt's page keys
    once and matches depths against a replica shadow set."""
    fps: List[int] = []
    fp = ROOT_FINGERPRINT
    for key in keys:
        fp = chain_fingerprint(fp, key)
        fps.append(fp)
    return fps


class _Node:
    __slots__ = ("key", "page", "children", "parent", "payload", "last_used")

    def __init__(self, key: Optional[PageKey], page: int, parent):
        self.key = key
        self.page = page
        self.children: dict = {}
        self.parent = parent
        self.payload: Any = None
        self.last_used = 0


class PrefixIndex:
    """Page-granular prompt-prefix trie over a :class:`BlockAllocator`.

    - :meth:`lookup` walks the longest matching chain, hands the caller one
      *reference* per matched non-NULL page (release with
      ``allocator.free``), and returns the terminal payload on an exact
      full match;
    - :meth:`insert` registers a freshly prefilled chain (the index takes
      its own reference per new page) with an optional terminal payload
      (the prefill's last-position logits);
    - :meth:`evict` reclaims LRU refcount-0 chains leaf-first until enough
      pages are free.
    """

    def __init__(self, allocator: BlockAllocator, registry: Any = None):
        self.alloc = allocator
        self.registry = registry
        self._root = _Node(None, NULL_PAGE, None)
        self._clock = 0
        self._nodes = 0
        # evictable_pages() memo, keyed by (allocator, trie) mutation
        # versions — the per-engine-step gauge export and per-submit gate
        # must not pay an O(trie) walk on steps that mutated nothing
        self._version = 0
        self._evictable_memo = (-1, -1, 0)
        if registry is not None:
            registry.counter(EVICTIONS_TOTAL)

    def __len__(self) -> int:
        return self._nodes

    def _touch(self, node: _Node) -> None:
        self._clock += 1
        node.last_used = self._clock

    # -- queries -----------------------------------------------------------

    def lookup(self, keys: Sequence[PageKey]) -> Tuple[List[int], Any]:
        """Longest-prefix match.  Returns ``(pages, payload)``: ``pages`` is
        the matched chain's physical page ids (NULL for padding pages); the
        caller now HOLDS one allocator reference on each non-NULL page and
        must ``free`` them when done.  ``payload`` is the terminal payload
        when the match covers *every* key (exact full-prompt hit), else
        None."""
        node = self._root
        pages: List[int] = []
        for key in keys:
            child = node.children.get(key)
            if child is None:
                break
            self._touch(child)
            self.alloc.retain(child.page)
            pages.append(child.page)
            node = child
        payload = node.payload if len(pages) == len(keys) else None
        return pages, payload

    def peek(self, keys: Sequence[PageKey]) -> Tuple[List[int], Any]:
        """:meth:`lookup` without side effects: the longest matching chain's
        pages and (on an exact full match) its terminal payload, taking NO
        allocator references and leaving LRU clocks untouched.  For
        presence probes — the fleet-transfer import path peeks before
        deciding how much of a chain it still needs to move."""
        node = self._root
        pages: List[int] = []
        for key in keys:
            child = node.children.get(key)
            if child is None:
                break
            pages.append(child.page)
            node = child
        payload = node.payload if len(pages) == len(keys) else None
        return pages, payload

    def find_fingerprint(self, fp: int):
        """Resolve a chain fingerprint back to the chain it names: the
        ``(keys, pages, payload)`` of the root-to-node chain whose rolling
        fingerprint equals ``fp``, or None when the index holds no such
        chain.  The export side of the fleet-global prefix directory —
        a directory hit carries only the 64-bit fingerprint, and the
        holding replica reconstructs the chain to serialize from it.  No
        references are taken (pair with :func:`~.transfer.export_chain`,
        which reads under the index's own reference)."""
        stack = [(self._root, ROOT_FINGERPRINT, [], [])]
        while stack:
            node, nfp, keys, pages = stack.pop()
            for child in node.children.values():
                cfp = chain_fingerprint(nfp, child.key)
                ckeys = keys + [child.key]
                cpages = pages + [child.page]
                if cfp == fp:
                    return list(ckeys), list(cpages), child.payload
                stack.append((child, cfp, ckeys, cpages))
        return None

    def insert(self, keys: Sequence[PageKey], pages: Sequence[int],
               payload: Any = None) -> None:
        """Register a chain (one page id per key; NULL for padding pages).
        New nodes take one index-owned reference on their page; existing
        nodes must already hold the SAME page (two chains with equal keys
        hold equal content — a mismatch is an engine bug).  ``payload``
        (when given) is stored on the terminal node."""
        if len(keys) != len(pages):
            raise ValueError(f"{len(keys)} keys vs {len(pages)} pages")
        node = self._root
        for key, page in zip(keys, pages):
            child = node.children.get(key)
            if child is None:
                child = _Node(key, int(page), node)
                node.children[key] = child
                self.alloc.retain(child.page)  # the index's own reference
                self._nodes += 1
            elif child.page != page:
                raise AssertionError(
                    f"prefix chain divergence: key {key!r} cached as page "
                    f"{child.page}, inserted as {page}")
            self._touch(child)
            node = child
        self._version += 1
        if payload is not None and node is not self._root:
            node.payload = payload

    def chain_fingerprints(self) -> Set[int]:
        """Fingerprint of every chain the index currently caches (one per
        node — each node terminates the chain of keys from the root down to
        it).  The truth a fleet router's per-replica shadow approximates;
        :meth:`~..serving.fleet.FleetRouter` resyncs from it after a replica
        restart so the shadow never credits an index that no longer holds
        the pages."""
        out: Set[int] = set()
        stack = [(self._root, ROOT_FINGERPRINT)]
        while stack:
            node, fp = stack.pop()
            for child in node.children.values():
                cfp = chain_fingerprint(fp, child.key)
                out.add(cfp)
                stack.append((child, cfp))
        return out

    def flush(self) -> int:
        """Drop EVERY cached chain at once — the index's reference on each
        non-NULL page is released (pages active slots or resume pins still
        hold stay allocated under THEIR references; index-only pages return
        to the free list).  Returns the number of nodes dropped.

        The live-weight swap path: cached KV (and terminal prefill logits)
        were computed under the outgoing params, so serving them to a
        post-swap admission would leak old-version output past the version
        boundary.  A flush is cheaper than being wrong — the cache re-warms
        under the new weights."""
        dropped = self._nodes
        for node in self._iter():
            self.alloc.free(node.page)  # no-op on NULL structure pages
        self._root = _Node(None, NULL_PAGE, None)
        self._nodes = 0
        self._version += 1
        return dropped

    # -- eviction ----------------------------------------------------------

    def _iter(self) -> Iterator[_Node]:
        stack = list(self._root.children.values())
        while stack:
            node = stack.pop()
            stack.extend(node.children.values())
            yield node

    def _evictable(self, node: _Node) -> bool:
        # leaf whose page nobody but the index references (NULL pages are
        # structure-only; dropping them frees nothing but may expose an
        # evictable parent)
        if node.children:
            return False
        return node.page == NULL_PAGE or self.alloc.refcount(node.page) == 1

    def evictable_pages(self) -> int:
        """Pages reclaimable by leaf-first eviction right now: a page counts
        only when it is index-only (refcount 1) AND its entire subtree is
        too — a pinned descendant shields every ancestor, since eviction
        removes leaves first.  (Engine chains pin whole prefixes, making
        the two conditions coincide; the count stays honest for any
        caller.)  Memoized on the allocator/trie mutation versions, so the
        steady decode path (no refcount changes) pays O(1), not O(trie)."""
        key = (self.alloc.version, self._version)
        if self._evictable_memo[:2] == key:
            return self._evictable_memo[2]
        total = 0

        def walk(node: _Node) -> bool:
            """True iff ``node``'s whole subtree (itself included) can go."""
            nonlocal total
            sub_ok = True
            for child in node.children.values():
                if not walk(child):
                    sub_ok = False
            if node.page != NULL_PAGE and self.alloc.refcount(node.page) != 1:
                return False
            if sub_ok and node.page != NULL_PAGE:
                total += 1
            return sub_ok

        for child in self._root.children.values():
            walk(child)
        self._evictable_memo = (*key, total)
        return total

    def evict(self, need_pages: int) -> int:
        """Evict least-recently-used unpinned leaves until ``need_pages``
        pages were freed (or nothing evictable remains).  Returns the pages
        actually freed."""
        freed = 0
        while freed < need_pages:
            leaf = min(
                (n for n in self._iter() if self._evictable(n)),
                key=lambda n: n.last_used, default=None)
            if leaf is None:
                break
            del leaf.parent.children[leaf.key]
            self._nodes -= 1
            self._version += 1
            if leaf.page != NULL_PAGE:
                self.alloc.free(leaf.page)
                freed += 1
                if self.registry is not None:
                    self.registry.counter(EVICTIONS_TOTAL).inc()
        return freed

    # -- invariants --------------------------------------------------------

    def assert_invariants(self) -> None:
        """Every cached non-NULL page is allocated with refcount >= 1 and
        owned by exactly one node; parent links are consistent."""
        seen: set = set()
        count = 0
        for node in self._iter():
            count += 1
            assert node.parent.children.get(node.key) is node, (
                "trie parent/child link broken")
            if node.page != NULL_PAGE:
                assert node.page not in seen, (
                    f"page {node.page} owned by two trie nodes")
                seen.add(node.page)
                assert self.alloc.refcount(node.page) >= 1, (
                    f"cached page {node.page} is not allocated")
        assert count == self._nodes, (
            f"node count drifted: walked {count}, tracked {self._nodes}")
