"""Device-side page pool for the paged KV cache (PagedAttention, Kwon et
al. SOSP '23, mapped onto static-shape pjit).

The contiguous serving cache reserves ``[B, max_total_len]`` KV per slot —
HBM scales with the *worst case* of every slot at once, and that, not
compute, caps concurrency.  The page pool breaks the coupling: one
preallocated ``[num_pages, page_size, kv_heads, head_dim]`` pair per layer,
and requests hold integer *block tables* mapping their logical cache pages
to physical pages.  Left-padding pages and unwritten decode tail pages
back onto the shared NULL page (index 0, content never written), and prompt
pages shared through the :class:`~.prefix.PrefixIndex` exist once.

Shapes are static — the pool is one allocation for the process lifetime,
pjit-compatible by construction: the decode program gathers ``pool[block
table]`` (the same ``[B, T]`` view the contiguous path attends over, so the
band-mask attention core is unchanged), and page writes are
``dynamic_update_slice`` at traced page ids.  Sharding matches the
contiguous caches: kv-heads over ``tp`` when divisible; the page axis is a
GLOBAL pool and stays unsharded over ``dp`` (block tables address arbitrary
pages — a dp-sharded page axis would turn every gather into a collective).
"""

from __future__ import annotations

from typing import Any, List, Optional, Tuple

import jax
import jax.numpy as jnp

from neuronx_distributed_tpu.parallel.mesh import (
    TENSOR_AXIS,
    get_mesh,
    model_parallel_is_initialized,
    named_sharding,
)
from neuronx_distributed_tpu.utils.logger import get_logger

logger = get_logger(__name__)

# registry counter: bytes the paged GATHER decode path spends
# rematerializing per-slot [B, T] contiguous K/V clones from the pool —
# what the block-table-native kernel (ops.paged_attention) saves.  Stays
# ZERO on the kernel path (the int8 acceptance gate: quantized serving
# with the kernel never materializes a dequantized history).
GATHER_BYTES_TOTAL = "kvcache/gather_bytes_total"


def init_page_pool_caches(
    num_layers: int,
    num_pages: int,
    page_size: int,
    num_kv_heads: int,
    head_dim: int,
    dtype: Any = jnp.bfloat16,
    quant: Optional[str] = None,
) -> List[Tuple[jax.Array, ...]]:
    """Zero page-pool caches ``[NP, page, NKV, D]`` per layer, kv-heads
    sharded over tp when divisible (the same policy as the contiguous
    ``init_kv_caches``); the page axis is unsharded — it is a global pool.

    ``quant="int8"`` switches each layer's entry from the fp pair
    ``(k, v)`` to the six-tuple ``(k int8, v int8, k_scale, k_zero,
    v_scale, v_zero)`` with one fp32 scale/zero per physical page (see
    :mod:`.quant`) — the structural marker the model's block-table
    scatter/gather keys its dequantize-in-the-gather path on."""
    shape = (num_pages, page_size, num_kv_heads, head_dim)
    if quant is None:
        caches: List[Tuple[jax.Array, ...]] = [
            (jnp.zeros(shape, dtype), jnp.zeros(shape, dtype))
            for _ in range(num_layers)
        ]
    elif quant == "int8":
        caches = [
            (jnp.zeros(shape, jnp.int8), jnp.zeros(shape, jnp.int8),
             jnp.zeros((num_pages,), jnp.float32),
             jnp.zeros((num_pages,), jnp.float32),
             jnp.zeros((num_pages,), jnp.float32),
             jnp.zeros((num_pages,), jnp.float32))
            for _ in range(num_layers)
        ]
    else:
        raise ValueError(f"unknown KV quantization {quant!r} "
                         "(supported: 'int8')")
    if model_parallel_is_initialized():
        mesh = get_mesh()
        kv_axes = (TENSOR_AXIS
                   if num_kv_heads % mesh.shape[TENSOR_AXIS] == 0 else None)
        if kv_axes is None and mesh.shape[TENSOR_AXIS] > 1:
            logger.warning(
                "page pool kv head dim (%d) not divisible by tp (%d); "
                "replicating", num_kv_heads, mesh.shape[TENSOR_AXIS])
        spec = named_sharding(None, None, kv_axes, None)
        scale_spec = named_sharding(None)  # per-page params: replicated
        caches = jax.tree.map(
            lambda x: jax.device_put(
                x, spec if x.ndim == 4 else scale_spec),
            caches)
    return caches


class PagePool:
    """The preallocated device pool plus its sizing arithmetic.

    ``caches`` is the live pytree the engine threads through the compiled
    paged phase fns (donated every decode step — treat the attribute as the
    initial value, not a persistent view).  The class is deliberately thin:
    page *accounting* lives in the host-side
    :class:`~.allocator.BlockAllocator`, device *programs* on the serving
    wrapper (``decode_pages`` / ``write_page`` / ``copy_page``)."""

    def __init__(
        self,
        num_layers: int,
        num_pages: int,
        page_size: int,
        num_kv_heads: int,
        head_dim: int,
        dtype: Any = jnp.bfloat16,
        quant: Optional[str] = None,
    ):
        if num_pages < 2:
            raise ValueError(
                f"num_pages must be >= 2 (page 0 is the NULL page), "
                f"got {num_pages}")
        if page_size < 1:
            raise ValueError(f"page_size must be >= 1, got {page_size}")
        self.num_layers = num_layers
        self.num_pages = num_pages
        self.page_size = page_size
        self.num_kv_heads = num_kv_heads
        self.head_dim = head_dim
        self.dtype = dtype
        self.quant = quant
        self.caches = init_page_pool_caches(
            num_layers, num_pages, page_size, num_kv_heads, head_dim, dtype,
            quant=quant)

    @property
    def page_bytes(self) -> int:
        """HBM bytes one page costs across all layers (k + v, plus the
        per-page scale/zero params under int8 quantization — honest
        accounting: the quantized pool pays for its metadata)."""
        from neuronx_distributed_tpu.kvcache.quant import page_layer_bytes

        return self.num_layers * page_layer_bytes(
            self.page_size, self.num_kv_heads, self.head_dim, self.quant,
            self.dtype)

    @property
    def total_bytes(self) -> int:
        return self.num_pages * self.page_bytes

    @staticmethod
    def pages_for_budget(budget_bytes: int, num_layers: int, page_size: int,
                         num_kv_heads: int, head_dim: int,
                         dtype: Any = jnp.bfloat16,
                         quant: Optional[str] = None) -> int:
        """How many pool pages a given HBM budget buys — the sizing half of
        the paged-vs-contiguous comparison (a contiguous ``[B, T]`` cache's
        budget is ``B * T / page_size`` pages).  ``quant="int8"`` roughly
        doubles the answer at a fixed budget versus bf16 (1 byte/element +
        four fp32 page params instead of 2 bytes/element)."""
        from neuronx_distributed_tpu.kvcache.quant import page_layer_bytes

        per_page = num_layers * page_layer_bytes(
            page_size, num_kv_heads, head_dim, quant, dtype)
        return max(int(budget_bytes // per_page), 0)
