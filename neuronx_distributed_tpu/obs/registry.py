"""Metric registry: counters, gauges, fixed-bucket histograms.

Low-overhead by construction — a metric update is a Python attribute write
plus (for histograms) one ``bisect``; no locks on the hot path (the training
loop is single-threaded; concurrent *registration* is guarded).  Two
serializations:

- the existing ``scalars.jsonl`` schema (``{"step", "tag", "value",
  "time"}`` per line, the same stream :class:`~..trainer.scalar_log
  .ScalarWriter` writes and :func:`~..trainer.scalar_log.read_scalars`
  reads), histograms flattened to ``name/count``, ``name/sum`` and
  cumulative ``name/le_<bound>`` tags;
- Prometheus text exposition (``# TYPE`` lines, ``_bucket{le=...}``
  cumulative histograms) for scrape-based collection.
"""

from __future__ import annotations

import bisect
import json
import math
import threading
import time
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

DEFAULT_BUCKETS: Tuple[float, ...] = (
    0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
    1.0, 2.5, 5.0, 10.0, 25.0, 50.0, 100.0,
)


class Counter:
    """Monotonic counter."""

    __slots__ = ("name", "value")

    def __init__(self, name: str):
        self.name = name
        self.value = 0.0

    def inc(self, amount: float = 1.0) -> None:
        if amount < 0:
            raise ValueError(f"counter {self.name}: negative increment {amount}")
        self.value += amount


class Gauge:
    """Last-value metric."""

    __slots__ = ("name", "value")

    def __init__(self, name: str):
        self.name = name
        self.value = 0.0

    def set(self, value: float) -> None:
        self.value = float(value)

    def inc(self, amount: float = 1.0) -> None:
        self.value += amount


class Histogram:
    """Fixed-boundary histogram (Prometheus semantics: ``boundaries[i]`` is
    the inclusive upper edge of bucket ``i``; one implicit ``+Inf`` bucket)."""

    __slots__ = ("name", "boundaries", "counts", "sum", "count")

    def __init__(self, name: str, boundaries: Sequence[float] = DEFAULT_BUCKETS):
        if not boundaries or list(boundaries) != sorted(boundaries):
            raise ValueError(
                f"histogram {name}: boundaries must be non-empty and sorted, "
                f"got {boundaries!r}")
        self.name = name
        self.boundaries = tuple(float(b) for b in boundaries)
        self.counts = [0] * (len(self.boundaries) + 1)  # last = +Inf overflow
        self.sum = 0.0
        self.count = 0

    def observe(self, value: float) -> None:
        value = float(value)
        if math.isnan(value):
            return  # NaN observations poison sum/mean; anomaly detectors own them
        self.counts[bisect.bisect_left(self.boundaries, value)] += 1
        self.sum += value
        self.count += 1

    def cumulative(self) -> List[Tuple[float, int]]:
        """``[(le, cum_count), ...]`` including the ``+Inf`` edge."""
        out, acc = [], 0
        for le, n in zip(self.boundaries, self.counts):
            acc += n
            out.append((le, acc))
        out.append((math.inf, acc + self.counts[-1]))
        return out


def _fmt_le(le: float) -> str:
    """Bucket-edge tag fragment: finite edges keep repr fidelity, inf -> 'inf'."""
    if math.isinf(le):
        return "inf"
    return repr(le) if le != int(le) else str(int(le))


class MetricRegistry:
    """Name-keyed home for the run's metrics.  ``counter`` / ``gauge`` /
    ``histogram`` are get-or-create (idempotent, so call sites never thread
    metric objects around); a name can hold only one metric kind."""

    def __init__(self):
        self._metrics: Dict[str, object] = {}
        self._lock = threading.Lock()

    def _get_or_create(self, name: str, cls, *args):
        m = self._metrics.get(name)
        if m is not None:
            if not isinstance(m, cls):
                raise ValueError(
                    f"metric {name!r} already registered as "
                    f"{type(m).__name__}, not {cls.__name__}")
            return m
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = cls(name, *args)
                self._metrics[name] = m
        return m

    def counter(self, name: str) -> Counter:
        return self._get_or_create(name, Counter)

    def gauge(self, name: str) -> Gauge:
        return self._get_or_create(name, Gauge)

    def histogram(self, name: str,
                  boundaries: Sequence[float] = DEFAULT_BUCKETS) -> Histogram:
        h = self._get_or_create(name, Histogram, boundaries)
        want = tuple(float(b) for b in boundaries)
        if h.boundaries != want:
            # silently returning the earlier buckets would misfile every
            # later observation; a mismatch is a call-site bug
            raise ValueError(
                f"histogram {name!r} already registered with boundaries "
                f"{h.boundaries}, requested {want}")
        return h

    def metrics(self) -> List[object]:
        with self._lock:
            return list(self._metrics.values())

    def _items(self) -> List[Tuple[str, object]]:
        """Sorted (name, metric) snapshot taken under the lock — the
        serializers iterate THIS, not the live dict, so a concurrent
        scrape (obs.metrics_server runs on its own thread) can never race
        a hot-path metric registration mid-iteration."""
        with self._lock:
            return sorted(self._metrics.items())

    # -- serialization -----------------------------------------------------

    def snapshot(self) -> Dict[str, object]:
        """Plain-data view: scalars map to floats, histograms to a dict."""
        out: Dict[str, object] = {}
        for name, m in self._items():
            if isinstance(m, Histogram):
                out[name] = {
                    "count": m.count,
                    "sum": m.sum,
                    "buckets": {_fmt_le(le): n for le, n in m.cumulative()},
                }
            else:
                out[name] = m.value
        return out

    def to_scalar_records(self, step: int, now: Optional[float] = None,
                          mono: Optional[float] = None) -> List[dict]:
        """Flatten every metric into ``scalars.jsonl``-schema records.

        Every record is stamped with BOTH clocks: ``time`` (wall — the
        shared epoch cross-host tooling merges on) and ``mono`` (the
        host-monotonic instant — skew-free ordering against the serving
        stack's monotonic-clocked spans and scheduler timestamps; wall
        time alone mis-sorts cross-replica artifacts after NTP steps)."""
        now = time.time() if now is None else now
        mono = time.monotonic() if mono is None else mono
        recs: List[dict] = []

        def rec(tag: str, value: float):
            value = float(value)
            if not math.isfinite(value):
                return  # a NaN gauge (e.g. diverged loss) must not poison
                # the JSONL stream; the anomaly detectors carry that signal
            recs.append({"step": int(step), "tag": tag, "value": value,
                         "time": now, "mono": mono})

        for name, m in self._items():
            if isinstance(m, Histogram):
                rec(f"{name}/count", m.count)
                rec(f"{name}/sum", m.sum)
                for le, cum in m.cumulative():
                    rec(f"{name}/le_{_fmt_le(le)}", cum)
            else:
                rec(name, m.value)
        return recs

    def dump_jsonl(self, path: str, step: int) -> None:
        """Append the current snapshot to a ``scalars.jsonl``-schema file."""
        records = self.to_scalar_records(step)
        with open(path, "a") as f:
            for r in records:
                f.write(json.dumps(r) + "\n")

    def prometheus_text(self) -> str:
        """Prometheus text exposition of the current state."""
        lines: List[str] = []
        for name, m in self._items():
            pname = _prom_name(name)
            if isinstance(m, Counter):
                lines.append(f"# TYPE {pname} counter")
                lines.append(f"{pname} {_prom_val(m.value)}")
            elif isinstance(m, Gauge):
                lines.append(f"# TYPE {pname} gauge")
                lines.append(f"{pname} {_prom_val(m.value)}")
            else:
                lines.append(f"# TYPE {pname} histogram")
                for le, cum in m.cumulative():
                    edge = "+Inf" if math.isinf(le) else _prom_val(le)
                    lines.append(f'{pname}_bucket{{le="{edge}"}} {cum}')
                lines.append(f"{pname}_sum {_prom_val(m.sum)}")
                lines.append(f"{pname}_count {m.count}")
        return "\n".join(lines) + ("\n" if lines else "")


def _prom_name(name: str) -> str:
    """Sanitize to the Prometheus metric-name charset."""
    out = [c if (c.isalnum() or c in "_:") else "_" for c in name]
    if out and out[0].isdigit():
        out.insert(0, "_")
    return "".join(out)


def _prom_val(v: float) -> str:
    if not math.isfinite(v):  # Prometheus text accepts NaN/+Inf/-Inf
        return "NaN" if math.isnan(v) else ("+Inf" if v > 0 else "-Inf")
    return repr(v) if v != int(v) else str(int(v))


def read_histograms(records: Iterable[dict]) -> Dict[str, dict]:
    """Reconstruct histogram summaries from ``scalars.jsonl``-schema records
    produced by :meth:`MetricRegistry.to_scalar_records` (latest step wins).
    Returns ``{name: {"count", "sum", "mean", "buckets": {le_str: cum}}}``."""
    latest: Dict[str, dict] = {}
    for r in records:
        tag = r.get("tag", "")
        for marker in ("/count", "/sum"):
            if tag.endswith(marker):
                name = tag[: -len(marker)]
                latest.setdefault(name, {"buckets": {}})[marker[1:]] = r["value"]
                break
        else:
            if "/le_" in tag:
                name, le = tag.rsplit("/le_", 1)
                latest.setdefault(name, {"buckets": {}})["buckets"][le] = r["value"]
    out = {}
    for name, h in latest.items():
        if not h["buckets"]:
            continue  # a plain tag that merely ends in /count or /sum
        count = h.get("count", 0.0)
        out[name] = {
            "count": count,
            "sum": h.get("sum", 0.0),
            "mean": (h.get("sum", 0.0) / count) if count else 0.0,
            "buckets": h["buckets"],
        }
    return out
