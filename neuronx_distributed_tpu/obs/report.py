"""Run-report builder: merge every persisted telemetry artifact into one
summary document.

Inputs (all optional — the report covers whatever exists):

- ``scalars.jsonl`` streams (the :class:`~..trainer.scalar_log.ScalarWriter`
  stream and/or the registry dumps in an obs dir);
- ``flight_record.json`` (the step flight recorder's last dump);
- ``hlo_audit.jsonl`` (one record per compiled executable);
- Chrome-trace timeline files (:class:`~..utils.timeline.Timeline` output).

The output validates against ``obs.schemas.SCHEMAS["obs_report"]`` and has a
markdown rendering for humans.  CLI: ``tools/obs_report.py``.
"""

from __future__ import annotations

import glob
import json
import os
import time
from typing import Dict, List, Optional, Sequence

from neuronx_distributed_tpu.obs import FLIGHT_FILE, HLO_AUDIT_FILE, SCALARS_FILE
from neuronx_distributed_tpu.obs.compile_ledger import (
    COMPILE_LEDGER_FILE,
    read_compile_ledger,
    summarize_compile_records,
)
from neuronx_distributed_tpu.obs.flight import read_flight
from neuronx_distributed_tpu.obs.hlo_audit import read_audits
from neuronx_distributed_tpu.obs.memory_ledger import (
    MEMORY_BREAKDOWN_FILE,
    read_memory_breakdown,
)
from neuronx_distributed_tpu.obs.perf import (
    PERF_ATTRIBUTION_FILE,
    summarize_perf,
)
from neuronx_distributed_tpu.obs.registry import read_histograms
from neuronx_distributed_tpu.obs.tracing import (
    PHASE_NAMES,
    TRACE_EVENTS_FILE,
    read_trace_events,
)

# v2 (tracing PR): the document gained the required "trace" section
# (per-request waterfalls from trace_events.jsonl; null when no trace).
# v3 (resource-ledger PR): required "compile" (compile_ledger.jsonl
# rollup) and "memory" (mem/* gauges + memory_breakdown.json) sections,
# both null when the run carried no ledger.
# v4 (fleet-health PR): required "alerts" section (alerts.jsonl rollup —
# firing count, worst severity, per-rule edges and time-firing; null when
# the run carried no health monitor), and --run-dir auto-discovers fleet
# layouts (per-replica scalars/serving_stats subdirectories merged via
# obs.aggregate, router_stats.jsonl rolled into the fleet section).
# v5 (perf-attribution PR): required "perf" section (per-family roofline
# attribution from perf_attribution.jsonl — device-time, achieved vs peak
# FLOP/s and bytes/s, compute-/memory-bound classification, MFU/MBU and
# tokens/s-ceiling rollup; replica streams merge additively; null when
# the run carried no perf profiler).
# v6 (fleet-autopilot PR): required "autopilot" section
# (autopilot_actions.jsonl rollup — action table, per-action and
# per-trigger counts, action rate over the covered mono span; null when
# the run carried no autopilot), and --compare gates on run B's action
# rate regressing past A's (a controller that has to act more often
# under the same workload is flapping or fighting a real regression).
# v7 (live-weights PR): required "weights" section (weight_swaps.jsonl
# rollup — swap/failure counts by source, per-replica version table with
# a monotonicity check, swap-latency stats; null when the run carried no
# swapper), and --compare gates on swap failures appearing in B when A's
# swaps all committed (a deploy pipeline that starts refusing envelopes
# under the same workload is a release regression).
OBS_REPORT_SCHEMA = "obs_report_v7"
SUPERVISOR_EVENTS_FILE = "supervisor_events.jsonl"
SERVING_STATS_FILE = "serving_stats.jsonl"
ROUTER_STATS_FILE = "router_stats.jsonl"
AUTOPILOT_ACTIONS_FILE = "autopilot_actions.jsonl"
WEIGHT_SWAPS_FILE = "weight_swaps.jsonl"


def _read_scalar_file(path: str) -> List[dict]:
    out = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if line:
                out.append(json.loads(line))
    return out


def _parse_timeline(path: str) -> List[dict]:
    """Parse a Timeline trace file: the Perfetto-tolerant JSON-array format
    has a header '[' and one ``{...},`` object per line with no closing
    bracket — fall back to line-wise parsing when strict JSON fails."""
    with open(path) as f:
        text = f.read()
    try:
        doc = json.loads(text)
        return doc if isinstance(doc, list) else doc.get("traceEvents", [])
    except json.JSONDecodeError:
        events = []
        for line in text.splitlines():
            line = line.strip().rstrip(",")
            if line.startswith("{"):
                try:
                    events.append(json.loads(line))
                except json.JSONDecodeError:
                    continue
        return events


def _summarize_scalars(records: List[dict],
                       histogram_names: frozenset = frozenset()) -> Dict[str, dict]:
    """Per-tag stream summary.  Histogram-flattened tags (``/le_*`` edges
    and the ``/count``/``/sum`` of any name in ``histogram_names``) are
    skipped — they are reconstructed into the histograms section instead,
    and min/max/mean over cumulative snapshots would be meaningless."""
    skip = {f"{h}/{suffix}" for h in histogram_names
            for suffix in ("count", "sum")}
    by_tag: Dict[str, dict] = {}
    for r in records:
        tag = r.get("tag")
        if tag is None or "/le_" in tag or tag in skip:
            continue
        s = by_tag.get(tag)
        v, step = float(r["value"]), int(r["step"])
        if s is None:
            by_tag[tag] = {
                "count": 1, "first_step": step, "last_step": step,
                "last": v, "min": v, "max": v, "_sum": v,
            }
        else:
            s["count"] += 1
            s["_sum"] += v
            s["min"] = min(s["min"], v)
            s["max"] = max(s["max"], v)
            if step >= s["last_step"]:
                s["last_step"], s["last"] = step, v
            s["first_step"] = min(s["first_step"], step)
    for s in by_tag.values():
        s["mean"] = s.pop("_sum") / s["count"]
    return by_tag


def _summarize_supervisor(path: str) -> dict:
    """Summarize a ``supervisor_events.jsonl`` stream: restart count, crash
    causes, time-to-recover (crash ``exit`` → next successful ``start``),
    and the final outcome — the "how many times did this run die and how
    fast did it come back" section of the run summary."""
    events = _read_scalar_file(path)  # same JSONL shape, different kind
    causes: List[str] = []
    recover_s: List[float] = []
    last_crash_time: Optional[float] = None
    gave_up = succeeded = False
    final_rc: Optional[int] = None
    for e in events:
        kind = e.get("event")
        if kind == "exit":
            final_rc = e.get("rc")
            if e.get("rc") != 0:
                causes.append(e.get("cause", "unknown"))
                last_crash_time = e.get("time")
        elif kind == "start" and last_crash_time is not None:
            recover_s.append(max(0.0, e["time"] - last_crash_time))
            last_crash_time = None
        elif kind == "giveup":
            gave_up = True
        elif kind == "success":
            succeeded = True
    return {
        "events": len(events),
        "attempts": max((e.get("attempt", 0) for e in events), default=0),
        "restarts": sum(1 for e in events if e.get("event") == "restart"),
        "crash_causes": causes,
        "recover_s": [round(s, 3) for s in recover_s],
        "mean_recover_s": (round(sum(recover_s) / len(recover_s), 3)
                           if recover_s else None),
        "succeeded": succeeded,
        "gave_up": gave_up,
        "final_rc": final_rc,
    }


def _summarize_host_blocked(histograms: Dict[str, dict]) -> Dict[str, dict]:
    """The async-hot-path overlap story, per subsystem: how much wall time
    the host spent blocked on explicit device fetches
    (``<sys>/host_blocked_ms``, written by the transfer audit) against the
    subsystem's step time — ``frac`` near 0 means the deferred/pipelined
    path is overlapping as designed, near 1 means every step drains the
    device."""
    out: Dict[str, dict] = {}
    for sys_name, step_hist in (("train", "train/step_time_ms"),
                                ("serving", "serving/step_ms")):
        hb = histograms.get(f"{sys_name}/host_blocked_ms")
        if not hb or not hb.get("count"):
            continue
        entry = {
            "blocked_ms_total": round(hb["sum"], 3),
            "blocked_ms_mean": round(hb["mean"], 3),
            "fetches": hb["count"],
        }
        steps = histograms.get(step_hist)
        if steps and steps.get("sum"):
            entry["frac"] = round(min(hb["sum"] / steps["sum"], 1.0), 4)
        out[sys_name] = entry
    return out


def _summarize_kvcache(scalars: Dict[str, dict]) -> Optional[dict]:
    """Paged-KV health from the registry's ``kvcache/*`` scalars: pool
    occupancy (in-use / total pages, with the prefix-cache-held share) and
    prefix-reuse effectiveness (page hit rate, prefills skipped outright,
    evictions, copy-on-writes).  None when the run served no paged engine."""
    total = scalars.get("kvcache/pages_total")
    if total is None or not total.get("last"):
        return None

    def last(tag):
        s = scalars.get(tag)
        return s["last"] if s else 0.0

    hits = last("kvcache/prefix_hits_total")
    misses = last("kvcache/prefix_misses_total")
    return {
        "pages_total": total["last"],
        "pages_in_use": last("kvcache/pages_in_use"),
        "pages_cached": last("kvcache/pages_cached"),
        "occupancy": round(last("kvcache/pages_in_use") / total["last"], 4),
        "prefix_hits": hits,
        "prefix_misses": misses,
        "prefix_hit_rate": (round(hits / (hits + misses), 4)
                            if hits + misses else None),
        "prefills_skipped": last("kvcache/prefill_skipped_total"),
        "evictions": last("kvcache/evictions_total"),
        "cow_copies": last("kvcache/cow_copies_total"),
        # bytes the gather decode path spent on [B, T] rematerialization;
        # 0 means the block-table-native kernel served every decode step
        "gather_bytes": last("kvcache/gather_bytes_total"),
    }


def _summarize_speculative(scalars: Dict[str, dict]) -> Optional[dict]:
    """Speculative-decoding health from the ``serving/spec_*_total``
    counters: draft acceptance rate (accepted/proposed — draft quality) and
    committed tokens per engine round (the tokens-per-step headline — the
    whole point of speculating is pushing it past 1).  None when the run
    served no speculative engine."""
    proposed = scalars.get("serving/spec_proposed_total")
    if proposed is None or not proposed.get("last"):
        return None

    def last(tag):
        s = scalars.get(tag)
        return s["last"] if s else 0.0

    p = proposed["last"]
    a = last("serving/spec_accepted_total")
    rounds = last("serving/spec_rounds_total")
    committed = last("serving/spec_committed_total")
    return {
        "proposed": p,
        "accepted": a,
        "acceptance_rate": round(a / p, 4) if p else None,
        "rounds": rounds,
        "committed": committed,
        "tokens_per_round": round(committed / rounds, 4) if rounds else None,
    }


def _summarize_tenancy(scalars: Dict[str, dict]) -> Optional[dict]:
    """Multi-tenant serving health from the ``tenancy/*`` registry scalars
    (plus ``kvcache/quant_pages_total``): adapter-pool residency and churn
    — how many adapters are device-resident, how much of the pool they
    hold, and the hit/load/eviction split (a high eviction count means the
    adapter pool thrashes — grow it or steer with adapter affinity).  None
    when the run served no multi-adapter or quantized engine."""
    resident = scalars.get("tenancy/adapters_resident")
    quant = scalars.get("kvcache/quant_pages_total")
    if (resident is None or resident.get("last") is None) and quant is None:
        return None

    def last(tag):
        s = scalars.get(tag)
        return s["last"] if s else 0.0

    hits = last("tenancy/adapter_hits_total")
    loads = last("tenancy/adapter_loads_total")
    return {
        "adapters_resident": last("tenancy/adapters_resident"),
        "adapter_pool_pages_in_use": last("tenancy/adapter_pool_pages_in_use"),
        "adapter_hits": hits,
        "adapter_loads": loads,
        "adapter_hit_rate": (round(hits / (hits + loads), 4)
                             if hits + loads else None),
        "adapter_evictions": last("tenancy/adapter_evictions_total"),
        "quant_pages": last("kvcache/quant_pages_total"),
    }


def _summarize_fleet(scalars: Dict[str, dict]) -> Optional[dict]:
    """Fleet-router health from the ``router/*`` registry scalars: pool
    size still in rotation, dispatch/requeue/failover accounting (requeues
    and failovers above 0 mean replicas died mid-run and their work moved),
    and the affinity story — how often the shadow steered a fingerprinted
    request to a replica already holding its pages, and the pool-wide
    prefix hit rate that steering exists to raise.  None when the run
    served no fleet."""
    dispatched = scalars.get("router/dispatched_total")
    if dispatched is None or not dispatched.get("last"):
        return None

    def last(tag):
        s = scalars.get(tag)
        return s["last"] if s else 0.0

    hits = last("router/affinity_hits_total")
    misses = last("router/affinity_misses_total")
    return {
        "replicas_alive": last("router/replicas_alive"),
        "dispatched": dispatched["last"],
        "requeued": last("router/requeued_total"),
        "failovers": last("router/failovers_total"),
        "restarts": last("router/restarts_total"),
        "retired": last("router/retired_total"),
        "affinity_hits": hits,
        "affinity_misses": misses,
        "affinity_hit_rate": (round(hits / (hits + misses), 4)
                              if hits + misses else None),
        "fleet_prefix_hit_rate": (
            round(last("router/fleet_prefix_hit_rate"), 4)
            if scalars.get("router/fleet_prefix_hit_rate") else None),
    }


def _hist_p99(hist: Optional[dict]) -> Optional[float]:
    """Approximate p99 from a cumulative-bucket histogram summary: the
    upper edge of the first bucket whose cumulative count covers 99% —
    coarse (bucket-resolution) but monotone, which is all the SLO line
    needs."""
    if not hist or not hist.get("count"):
        return None
    import math

    target = 0.99 * hist["count"]
    for le, cum in hist["buckets"].items():
        if cum >= target:
            try:
                v = float(le)
            except ValueError:
                return None
            # the overflow bucket's edge renders as "inf" — float() parses
            # it happily, but "p99 ~infms" is not a number worth printing
            return None if math.isinf(v) else v
    return None


def _summarize_slo(scalars: Dict[str, dict],
                   histograms: Dict[str, dict]) -> Optional[dict]:
    """SLO-serving health from the priority scheduler's counters and the
    per-class latency histograms: preemptions (batch victims parked for
    interactive heads), load shed at admission (infeasible deadlines),
    expiries caught immediately before prefill dispatch, chunked-prefill
    dispatches, and the per-class TTFT / inter-token p99s the whole
    subsystem exists to keep flat.  None when the run used none of the SLO
    machinery."""
    names = ("serving/preemptions_total", "serving/shed_total",
             "serving/expired_before_prefill_total",
             "serving/prefill_chunks_total")

    def last(tag):
        s = scalars.get(tag)
        return s["last"] if s else 0.0

    per_class = {}
    for cls in ("interactive", "batch"):
        ttft = histograms.get(f"serving/ttft_ms_{cls}")
        inter = histograms.get(f"serving/intertoken_ms_{cls}")
        if (ttft and ttft.get("count")) or (inter and inter.get("count")):
            per_class[cls] = {
                "requests": ttft["count"] if ttft else 0,
                "ttft_p99_ms": _hist_p99(ttft),
                "intertoken_p99_ms": _hist_p99(inter),
            }
    if not per_class and not any(last(n) for n in names):
        return None
    return {
        "preemptions": last("serving/preemptions_total"),
        "shed": last("serving/shed_total"),
        "expired_before_prefill": last(
            "serving/expired_before_prefill_total"),
        "prefill_chunks": last("serving/prefill_chunks_total"),
        "classes": per_class,
    }


def _summarize_compile(scalars: Dict[str, dict],
                       ledger_records: List[dict],
                       histograms: Dict[str, dict]) -> Optional[dict]:
    """The "compile" health section: the compile ledger's rollup (per-
    family compiles / cold wall-time / distinct keys / evictions, storm and
    thrash counts) joined with the live ``trace/compile*`` scalars.  None
    when the run carried no compile ledger."""
    if not ledger_records and scalars.get("trace/compiles_total") is None:
        return None

    def last(tag):
        s = scalars.get(tag)
        return s["last"] if s else 0.0

    out = summarize_compile_records(ledger_records, cache={
        "hits": last("trace/compiled_cache_hits_total"),
        "misses": last("trace/compiled_cache_misses_total"),
        "evictions": last("trace/compiled_cache_evictions_total"),
    })
    if not ledger_records:
        # scalars-only view (the jsonl was not collected): keep the counts
        out["compiles"] = last("trace/compiles_total")
        out["storms"] = last("trace/compile_storms_total")
        out["thrash_warnings"] = last("trace/compile_thrash_total")
        h = histograms.get("trace/compile_ms")
        if h:
            out["cold_ms_total"] = round(h.get("sum", 0.0), 3)
    return out


def _summarize_memory(scalars: Dict[str, dict],
                      breakdown: Optional[dict]) -> Optional[dict]:
    """The "memory" health section: per-subsystem bytes + peak watermarks
    from ``memory_breakdown.json`` when present, else reconstructed from
    the live ``mem/*_bytes`` gauges.  None when the run carried no memory
    ledger."""
    if breakdown is not None:
        return {
            "subsystems": breakdown["subsystems"],
            "total_bytes": breakdown["total_bytes"],
            "peak_total_bytes": breakdown["peak_total_bytes"],
            "device": breakdown.get("device"),
            "top": breakdown.get("top", []),
            "reason": breakdown.get("reason"),
        }
    subs: Dict[str, dict] = {}
    device: Dict[str, float] = {}
    for tag, s in scalars.items():
        if not tag.startswith("mem/"):
            continue
        name = tag[len("mem/"):]
        if name.startswith(("device_", "live_array")):
            device[name] = s["last"]
        elif name.endswith("_peak_bytes"):
            subs.setdefault(name[:-len("_peak_bytes")], {})["peak_bytes"] = \
                s["last"]
        elif name.endswith("_bytes"):
            subs.setdefault(name[:-len("_bytes")], {})["bytes"] = s["last"]
    if not subs and not device:
        return None
    for v in subs.values():
        v.setdefault("bytes", 0.0)
        v.setdefault("peak_bytes", v["bytes"])
    total = sum(v["bytes"] for v in subs.values())
    return {
        "subsystems": subs,
        "total_bytes": total,
        "peak_total_bytes": sum(v["peak_bytes"] for v in subs.values()),
        "device": device or None,
        "top": sorted(([k, v["bytes"]] for k, v in subs.items()),
                      key=lambda kv: -kv[1])[:5],
        "reason": None,
    }


def compare_resources(run_a: str, run_b: str,
                      compile_threshold: float = 0.0,
                      mem_threshold: float = 0.05,
                      mfu_threshold: float = 0.05,
                      autopilot_threshold: float = 0.5) -> dict:
    """Run-to-run compile/memory/alert/perf/autopilot regression diff
    (``tools/obs_report.py --compare RUN_A RUN_B``): reads each run dir's
    ``compile_ledger.jsonl``, ``memory_breakdown.json``,
    ``*alerts.jsonl``, ``*perf_attribution.jsonl`` and
    ``*autopilot_actions.jsonl`` and flags B against A — more compiles
    than ``(1 + compile_threshold) * A`` (or any storm in B), any
    subsystem's peak bytes past ``(1 + mem_threshold) * A``'s, any alert
    RULE that fired in B without firing in A (a new alert under the same
    workload is a health regression, threshold-free), B's MFU sagging
    below ``(1 - mfu_threshold) * A``'s (same workload, less of the
    device's peak — the perf regression the roofline profiler exists to
    catch), or B's autopilot action rate past
    ``(1 + autopilot_threshold) * A``'s (a controller that has to act
    more often under the same workload is flapping, or fighting a real
    regression upstream of it; actions appearing in B when A's autopilot
    never acted regress threshold-free).  ``*weight_swaps.jsonl`` adds
    the deploy gates: swap FAILURES appearing in B when every swap in A
    committed, and any replica whose weights_version went non-monotonic
    (both threshold-free — a refused envelope or a version rollback under
    the same deploy pipeline is a release regression, not noise).
    Returns ``{"a", "b", "compile", "memory", "alerts", "perf",
    "autopilot", "weights", "regressions", "regressed", "markdown"}``."""
    def load(run_dir):
        cl_path = os.path.join(run_dir, COMPILE_LEDGER_FILE)
        mb_path = os.path.join(run_dir, MEMORY_BREAKDOWN_FILE)
        compile_sum = (summarize_compile_records(read_compile_ledger(cl_path))
                       if os.path.exists(cl_path) else None)
        breakdown = (read_memory_breakdown(mb_path)
                     if os.path.exists(mb_path) else None)
        alerts = summarize_alerts(
            sorted(glob.glob(os.path.join(run_dir, "*alerts.jsonl"))))
        from neuronx_distributed_tpu.obs.aggregate import merge_perf_files

        perf = summarize_perf(merge_perf_files(sorted(
            glob.glob(os.path.join(run_dir, f"*{PERF_ATTRIBUTION_FILE}")))))
        autopilot = summarize_autopilot(sorted(glob.glob(
            os.path.join(run_dir, f"*{AUTOPILOT_ACTIONS_FILE}"))))
        weights = summarize_weights(sorted(glob.glob(
            os.path.join(run_dir, f"*{WEIGHT_SWAPS_FILE}"))))
        return compile_sum, breakdown, alerts, perf, autopilot, weights

    ca, ma, aa, perf_a, ap_a, wt_a = load(run_a)
    cb, mb, ab, perf_b, ap_b, wt_b = load(run_b)
    regressions: List[str] = []
    lines = ["# Resource regression diff", "",
             f"- A: `{run_a}`", f"- B: `{run_b}`", ""]

    lines += ["## Compile", "",
              "| metric | A | B |", "|---|---|---|"]
    for key in ("compiles", "cold_ms_total", "cold_ms_max", "storms",
                "thrash_warnings", "evictions"):
        va = ca.get(key, 0) if ca else "n/a"
        vb = cb.get(key, 0) if cb else "n/a"
        lines.append(f"| {key} | {va} | {vb} |")
    if ca and cb:
        if cb["compiles"] > ca["compiles"] * (1.0 + compile_threshold):
            regressions.append(
                f"compiles regressed: {ca['compiles']} -> {cb['compiles']} "
                f"(threshold {compile_threshold:.0%})")
        if cb["storms"] > 0 and cb["storms"] > ca["storms"]:
            regressions.append(
                f"compile storms appeared: {ca['storms']} -> {cb['storms']}")
    lines.append("")

    lines += ["## Memory (peak bytes per subsystem)", "",
              "| subsystem | A | B |", "|---|---|---|"]
    subs_a = (ma or {}).get("subsystems", {})
    subs_b = (mb or {}).get("subsystems", {})
    for name in sorted(set(subs_a) | set(subs_b)):
        pa = subs_a.get(name, {}).get("peak_bytes")
        pb = subs_b.get(name, {}).get("peak_bytes")
        lines.append(f"| {name} | {pa if pa is not None else 'n/a'} "
                     f"| {pb if pb is not None else 'n/a'} |")
        if pa and pb and pb > pa * (1.0 + mem_threshold):
            regressions.append(
                f"memory regressed: {name} peak {pa:,.0f} -> {pb:,.0f} "
                f"bytes (threshold {mem_threshold:.0%})")
        elif not pa and pb and ma is not None:
            # a consumer with no baseline (absent or zero-peak in A) has no
            # threshold to compare against — an arbitrarily large NEW
            # footprint must not pass a regression gate silently
            regressions.append(
                f"memory regressed: new subsystem {name} appeared in B "
                f"({pb:,.0f} peak bytes, no baseline in A)")
    lines.append("")

    def fired_rules(alerts):
        if alerts is None:
            return {}
        return {name: agg for name, agg in alerts["rules"].items()
                if agg["fired"]}

    fa, fb = fired_rules(aa), fired_rules(ab)
    if aa is not None or ab is not None:
        lines += ["## Alerts (firing edges)", "",
                  "| rule | A | B |", "|---|---|---|"]
        for name in sorted(set(fa) | set(fb)):
            va = fa[name]["fired"] if name in fa else (
                0 if aa is not None else "n/a")
            vb = fb[name]["fired"] if name in fb else (
                0 if ab is not None else "n/a")
            lines.append(f"| {name} | {va} | {vb} |")
        if not (fa or fb):
            lines.append("| (none fired) | 0 | 0 |")
        lines.append("")
    if aa is not None:
        # a rule firing in B that never fired in A is a regression under
        # the same workload — no threshold, presence is the signal
        for name in sorted(set(fb) - set(fa)):
            regressions.append(
                f"alerts regressed: rule {name!r} fired "
                f"{fb[name]['fired']}x in B (severity "
                f"{fb[name]['severity']}), never in A")

    ra = (perf_a or {}).get("rollup")
    rb = (perf_b or {}).get("rollup")
    if perf_a is not None or perf_b is not None:
        lines += ["## Perf (roofline rollup)", "",
                  "| metric | A | B |", "|---|---|---|"]
        for key in ("mfu", "mbu", "pct_roofline", "device_ms"):
            va = ra.get(key) if ra else None
            vb = rb.get(key) if rb else None
            fmt = (lambda v, k=key: "n/a" if v is None else
                   (f"{v:,.1f}" if k == "device_ms" else f"{v:.1%}"))
            lines.append(f"| {key} | {fmt(va)} | {fmt(vb)} |")
        lines.append("")
    if ra and rb and ra.get("mfu") and \
            rb["mfu"] < ra["mfu"] * (1.0 - mfu_threshold):
        regressions.append(
            f"mfu regressed: {ra['mfu']:.2%} -> {rb['mfu']:.2%} "
            f"(threshold {mfu_threshold:.0%})")

    if ap_a is not None or ap_b is not None:
        lines += ["## Autopilot (remediation actions)", "",
                  "| metric | A | B |", "|---|---|---|"]
        for key in ("actions", "span_s", "rate_per_s"):
            va = ap_a.get(key) if ap_a else None
            vb = ap_b.get(key) if ap_b else None
            fmt = lambda v: "n/a" if v is None else (
                f"{v:.4g}" if isinstance(v, float) else str(v))
            lines.append(f"| {key} | {fmt(va)} | {fmt(vb)} |")
        lines.append("")
    if ap_a is not None and ap_b is not None:
        na, nb = ap_a["actions"], ap_b["actions"]
        rate_a, rate_b = ap_a["rate_per_s"], ap_b["rate_per_s"]
        if na == 0 and nb > 0:
            # A's autopilot watched the same workload and never had to
            # act — any action in B is a regression, threshold-free
            regressions.append(
                f"autopilot regressed: {nb} action(s) in B, none in A")
        elif rate_a is not None and rate_b is not None and \
                rate_b > rate_a * (1.0 + autopilot_threshold):
            regressions.append(
                f"autopilot regressed: action rate {rate_a:.4g}/s -> "
                f"{rate_b:.4g}/s (threshold {autopilot_threshold:.0%})")
        elif (rate_a is None or rate_b is None) and na > 0 and \
                nb > na * (1.0 + autopilot_threshold):
            # too few actions on one side to form a rate — fall back to
            # gating on the raw count
            regressions.append(
                f"autopilot regressed: {na} -> {nb} action(s) "
                f"(threshold {autopilot_threshold:.0%})")

    if wt_a is not None or wt_b is not None:
        lines += ["## Weights (live swaps)", "",
                  "| metric | A | B |", "|---|---|---|"]
        for key in ("swaps", "failures", "monotonic"):
            va = wt_a.get(key) if wt_a else None
            vb = wt_b.get(key) if wt_b else None
            fmt = lambda v: "n/a" if v is None else str(v)
            lines.append(f"| {key} | {fmt(va)} | {fmt(vb)} |")
        lines.append("")
    if wt_b is not None:
        # both gates are threshold-free: a deploy pipeline that starts
        # refusing envelopes (when A's swaps all committed), or ANY
        # version rollback, is a release regression
        if wt_a is not None and wt_a["failures"] == 0 \
                and wt_b["failures"] > 0:
            regressions.append(
                f"weights regressed: {wt_b['failures']} swap failure(s) "
                "in B, none in A")
        if not wt_b["monotonic"]:
            bad = sorted(rid for rid, rep in wt_b["replicas"].items()
                         if not rep["monotonic"])
            regressions.append(
                "weights regressed: weights_version went non-monotonic "
                f"in B (replica(s) {', '.join(bad)})")

    if regressions:
        lines += ["## Regressions", ""] + [f"- {r}" for r in regressions] \
            + [""]
    else:
        lines += ["No regressions past thresholds.", ""]
    return {
        "a": run_a, "b": run_b,
        "compile": {"a": ca, "b": cb},
        "memory": {"a": ma and {k: ma[k] for k in
                                ("subsystems", "total_bytes",
                                 "peak_total_bytes")},
                   "b": mb and {k: mb[k] for k in
                                ("subsystems", "total_bytes",
                                 "peak_total_bytes")}},
        "alerts": {"a": aa, "b": ab},
        "perf": {"a": ra, "b": rb},
        "autopilot": {"a": ap_a, "b": ap_b},
        "weights": {"a": wt_a, "b": wt_b},
        "regressions": regressions,
        "regressed": bool(regressions),
        "markdown": "\n".join(lines),
    }


def summarize_alerts(paths: Sequence[str]) -> Optional[dict]:
    """The "alerts" section: roll every ``alerts.jsonl`` edge stream into
    firing count, worst severity among still-firing alerts, and per-rule
    edge counts + total time-firing (fire→resolve pairs on the monotonic
    clock; an unresolved alert accrues until the stream's last stamp).
    Returns None when no alert files exist (the report key is null, not
    {}) — an existing-but-quiet file reports zero edges."""
    from neuronx_distributed_tpu.obs.health import read_alerts, worst_severity

    records: List[dict] = []
    files = 0
    for p in paths:
        if os.path.exists(p):
            files += 1
            records.extend(read_alerts(p))
    if not files:
        return None
    records.sort(key=lambda r: r.get("mono", 0.0))
    last_mono = records[-1].get("mono", 0.0) if records else 0.0
    per_key: Dict[tuple, dict] = {}
    for r in records:
        key = (r.get("rule", "?"), r.get("key", ""), r.get("replica", -1))
        st = per_key.setdefault(key, {
            "rule": key[0], "severity": r.get("severity", "warn"),
            "fired": 0, "resolved": 0, "firing_since": None,
            "time_firing_s": 0.0})
        st["severity"] = r.get("severity", st["severity"])
        if r.get("state") == "firing":
            st["fired"] += 1
            st["firing_since"] = r.get("mono", 0.0)
        else:
            st["resolved"] += 1
            if st["firing_since"] is not None:
                st["time_firing_s"] += max(
                    r.get("mono", 0.0) - st["firing_since"], 0.0)
                st["firing_since"] = None
    rules: Dict[str, dict] = {}
    firing_now: List[dict] = []
    for st in per_key.values():
        if st["firing_since"] is not None:  # never resolved: accrue to end
            st["time_firing_s"] += max(last_mono - st["firing_since"], 0.0)
            firing_now.append(st)
        agg = rules.setdefault(st["rule"], {
            "severity": st["severity"], "fired": 0, "resolved": 0,
            "firing": 0, "time_firing_s": 0.0})
        agg["fired"] += st["fired"]
        agg["resolved"] += st["resolved"]
        agg["firing"] += int(st["firing_since"] is not None)
        agg["time_firing_s"] = round(
            agg["time_firing_s"] + st["time_firing_s"], 6)
        if _sev_rank(st["severity"]) > _sev_rank(agg["severity"]):
            agg["severity"] = st["severity"]
    top = sorted(((name, agg["time_firing_s"])
                  for name, agg in rules.items()),
                 key=lambda kv: -kv[1])[:5]
    return {
        "files": files,
        "records": len(records),
        "firing": len(firing_now),
        "worst_severity": worst_severity(
            [st["severity"] for st in firing_now]),
        "rules": dict(sorted(rules.items())),
        "top_firing_s": [[name, s] for name, s in top if s > 0],
    }


def _sev_rank(severity: str) -> int:
    from neuronx_distributed_tpu.obs.health import _SEV_ORDER

    return _SEV_ORDER.get(severity, 0)


def summarize_autopilot(paths: Sequence[str],
                        tail: int = 20) -> Optional[dict]:
    """The "autopilot" section: roll every ``autopilot_actions.jsonl``
    stream into per-action and per-trigger counts, the action rate over
    the covered monotonic span, and the last ``tail`` actions as table
    rows.  Returns None when no action files exist (the report key is
    null, not {}) — an existing-but-quiet file reports zero actions (an
    autopilot that never had to act is the healthy outcome, and distinct
    from no autopilot at all)."""
    records: List[dict] = []
    files = 0
    for p in paths:
        if not os.path.exists(p):
            continue
        files += 1
        with open(p) as f:
            for line in f:
                line = line.strip()
                if line:
                    records.append(json.loads(line))
    if not files:
        return None
    records.sort(key=lambda r: r.get("mono", 0.0))
    by_action: Dict[str, int] = {}
    triggers: Dict[str, dict] = {}
    modes: Dict[str, int] = {}
    for r in records:
        action = r.get("action", "?")
        by_action[action] = by_action.get(action, 0) + 1
        modes[r.get("mode", "?")] = modes.get(r.get("mode", "?"), 0) + 1
        trig = triggers.setdefault(r.get("trigger", "?"), {
            "actions": 0, "by_action": {}, "replicas": set()})
        trig["actions"] += 1
        trig["by_action"][action] = trig["by_action"].get(action, 0) + 1
        rid = r.get("replica", -1)
        if rid >= 0:
            trig["replicas"].add(rid)
    for trig in triggers.values():
        trig["replicas"] = sorted(trig["replicas"])
        trig["by_action"] = dict(sorted(trig["by_action"].items()))
    span_s = (records[-1].get("mono", 0.0) - records[0].get("mono", 0.0)
              if len(records) >= 2 else 0.0)
    rate = (len(records) / span_s) if span_s > 0 else None
    slim = [{"mono": r.get("mono", 0.0),
             "action": r.get("action", "?"),
             "trigger": r.get("trigger", "?"),
             "replica": r.get("replica", -1),
             "mode": r.get("mode", "?"),
             "budget_remaining": r.get("budget_remaining", -1),
             "detail": r.get("detail", {})} for r in records]
    return {
        "files": files,
        "actions": len(records),
        "by_action": dict(sorted(by_action.items())),
        "triggers": dict(sorted(triggers.items())),
        "modes": dict(sorted(modes.items())),
        "span_s": round(span_s, 6),
        "rate_per_s": rate,
        "last": slim[-1] if slim else None,
        "tail": slim[-tail:],
    }


def summarize_weights(paths: Sequence[str],
                      tail: int = 20) -> Optional[dict]:
    """The "weights" section: roll every ``weight_swaps.jsonl`` stream
    (solo engines write one; a fleet rolling update writes one per
    replica) into committed/failed swap counts by source, swap-latency
    stats, and a per-replica version table with a monotonicity check —
    the invariant a live deploy must never break.  Returns None when no
    swap files exist (the report key is null, not {}) — an
    existing-but-quiet file reports zero swaps (an engine that installed
    a swapper and never deployed is distinct from no swapper at all)."""
    records: List[dict] = []
    files = 0
    for p in paths:
        if not os.path.exists(p):
            continue
        files += 1
        with open(p) as f:
            for line in f:
                line = line.strip()
                if line:
                    records.append(json.loads(line))
    if not files:
        return None
    records.sort(key=lambda r: r.get("mono", 0.0))
    by_source: Dict[str, int] = {}
    replicas: Dict[int, dict] = {}
    swaps = failures = 0
    ms: List[float] = []
    for r in records:
        rid = int(r.get("replica", -1))
        rep = replicas.setdefault(rid, {
            "swaps": 0, "failures": 0, "version": 0, "monotonic": True})
        src = r.get("source", "?")
        if r.get("ok"):
            swaps += 1
            by_source[src] = by_source.get(src, 0) + 1
            v = int(r.get("version", 0))
            if v <= rep["version"]:
                rep["monotonic"] = False
            rep["version"] = max(rep["version"], v)
            rep["swaps"] += 1
            if r.get("swap_ms") is not None:
                ms.append(float(r["swap_ms"]))
        else:
            failures += 1
            rep["failures"] += 1
    slim = [{"mono": r.get("mono", 0.0),
             "event": r.get("event", "?"),
             "version": r.get("version", 0),
             "source": r.get("source", "?"),
             "ok": bool(r.get("ok")),
             "swap_ms": r.get("swap_ms"),
             "error": r.get("error"),
             "replica": r.get("replica", -1)} for r in records]
    return {
        "files": files,
        "swaps": swaps,
        "failures": failures,
        "by_source": dict(sorted(by_source.items())),
        "replicas": {str(rid): rep
                     for rid, rep in sorted(replicas.items())},
        "monotonic": all(rep["monotonic"] for rep in replicas.values()),
        "swap_ms_mean": (round(sum(ms) / len(ms), 3) if ms else None),
        "swap_ms_max": (round(max(ms), 3) if ms else None),
        "last": slim[-1] if slim else None,
        "tail": slim[-tail:],
    }


def read_serving_stats(path: str) -> List[dict]:
    """Read a ``serving_stats.jsonl`` stream ACROSS schema versions: v4
    records (pre-tracing) lack ``decode_steps``/``prefill_chunks``/
    ``preempted_ms``/``trace_id``/``mono``, v5 records (pre-live-weights)
    lack ``weights_version``; they are filled with defaults so downstream
    consumers never branch on the version (version 0 is exactly right for
    a pre-swap-era record: the process-start weights)."""
    out: List[dict] = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            rec = json.loads(line)
            rec.setdefault("decode_steps", 0)
            rec.setdefault("prefill_chunks", 0)
            rec.setdefault("preempted_ms", 0.0)
            rec.setdefault("trace_id", None)
            rec.setdefault("mono", None)
            rec.setdefault("weights_version", 0)
            out.append(rec)
    return out


def summarize_trace(trace_paths: Sequence[str],
                    stats_records: Sequence[dict] = (),
                    top: int = 5) -> Optional[dict]:
    """The ``--trace`` section: per-request waterfalls reconstructed from
    ``trace_events.jsonl`` spans.

    Spans group by fleet-global ``request_id`` (one stitched trace per
    request, across replicas and failover hops); the four PHASE spans
    (queue, prefill, decode, preempted) tile a request's lifetime, so
    their per-phase sums ARE the latency decomposition.  ``stats_records``
    (``serving_stats`` v4/v5) link each waterfall to its terminal record
    via ``trace_id`` for the reported-latency cross-check.  Returns None
    when no spans exist (the report's "trace" key is null, not {})."""
    spans: List[dict] = []
    for p in trace_paths:
        if os.path.exists(p):
            spans.extend(read_trace_events(p))
    if not spans:
        return None
    stats_by_trace = {r["trace_id"]: r for r in stats_records
                      if r.get("trace_id") is not None}

    by_req: Dict[int, List[dict]] = {}
    for s in spans:
        rid = s.get("request_id", -1)
        if rid >= 0:
            by_req.setdefault(rid, []).append(s)

    requests: List[dict] = []
    agg_phases = {name: 0.0 for name in PHASE_NAMES}
    agg_migrate = 0.0
    for rid, group in by_req.items():
        phases = {name: 0.0 for name in PHASE_NAMES}
        hops = 0
        migrate_ms = 0.0
        migrations = 0
        migrate_pages = 0
        replicas = set()
        state = None
        roots = 0
        for s in group:
            dur = max(s["t_end"] - s["t_start"], 0.0) * 1e3
            if s["name"] in phases:
                phases[s["name"]] += dur
            replicas.add(s["replica"])
            attrs = s.get("attrs", {})
            if s["name"] == "request":
                roots += 1
                hops = max(hops, int(attrs.get("hop", 0)))
                if attrs.get("state") is not None:
                    state = attrs["state"]
            elif s["name"] == "route/requeue":
                hops = max(hops, int(attrs.get("hop", 0)))
            elif s["name"] == "route/migrate":
                # disagg hop: KV export/import wall time (aborted fills
                # count too — they cost the same router time)
                migrate_ms += dur
                migrations += 1
                migrate_pages += int(attrs.get("pages", 0))
        for name, ms in phases.items():
            agg_phases[name] += ms
        agg_migrate += migrate_ms
        total = sum(phases.values())
        entry = {
            "request_id": rid,
            "state": state,
            "total_ms": round(total, 3),
            "queue_ms": round(phases["queue"], 3),
            "prefill_ms": round(phases["prefill"], 3),
            "decode_ms": round(phases["decode"], 3),
            "preempted_ms": round(phases["preempted"], 3),
            "migrate_ms": round(migrate_ms, 3),
            "migrations": migrations,
            "migrate_pages": migrate_pages,
            "hops": hops,
            "replicas": sorted(replicas - {-1}) or [-1],
            "spans": len(group),
            "window_ms": round(
                (max(s["t_end"] for s in group)
                 - min(s["t_start"] for s in group)) * 1e3, 3),
        }
        rec = stats_by_trace.get(rid)
        if rec is not None:
            entry["stats_total_ms"] = rec.get("total_ms")
            entry["stats_state"] = rec.get("state")
        requests.append(entry)

    requests.sort(key=lambda e: -e["total_ms"])
    by_phase = {k: round(v, 3) for k, v in agg_phases.items()}
    # migrate rides beside the four lifetime phases (it overlaps none of
    # them: the hop happens between withdrawal and re-submission)
    by_phase["migrate"] = round(agg_migrate, 3)
    return {
        "files": len([p for p in trace_paths if os.path.exists(p)]),
        "spans": len(spans),
        "requests": len(requests),
        "by_phase_ms": by_phase,
        "slowest": requests[:top],
    }


def _summarize_timeline(paths: Sequence[str]) -> dict:
    events = instants = 0
    dur_by_name: Dict[str, float] = {}
    markers: List[dict] = []
    for path in paths:
        for e in _parse_timeline(path):
            ph = e.get("ph")
            if ph == "X":
                events += 1
                dur_by_name[e.get("name", "?")] = (
                    dur_by_name.get(e.get("name", "?"), 0.0)
                    + float(e.get("dur", 0.0)) / 1e3)
            elif ph == "i":
                instants += 1
                if e.get("name", "").startswith("anomaly/"):
                    markers.append({"name": e["name"],
                                    "args": e.get("args", {})})
    top = dict(sorted(dur_by_name.items(), key=lambda kv: -kv[1])[:20])
    return {
        "files": len(list(paths)),
        "events": events,
        "instants": instants,
        "total_ms_by_name": top,
        "anomaly_markers": markers[:50],
    }


def build_report(
    run_dir: Optional[str] = None,
    scalar_paths: Sequence[str] = (),
    flight_path: Optional[str] = None,
    hlo_audit_path: Optional[str] = None,
    timeline_paths: Sequence[str] = (),
    supervisor_events_path: Optional[str] = None,
    trace_paths: Sequence[str] = (),
    serving_stats_path: Optional[str] = None,
    compile_ledger_path: Optional[str] = None,
    memory_breakdown_path: Optional[str] = None,
    alerts_paths: Sequence[str] = (),
    router_stats_path: Optional[str] = None,
    perf_paths: Sequence[str] = (),
    autopilot_paths: Sequence[str] = (),
    weights_paths: Sequence[str] = (),
    tail: int = 10,
) -> dict:
    """Merge the artifacts into one summary document.

    ``run_dir`` seeds the default artifact locations (``scalars.jsonl``,
    ``flight_record.json``, ``hlo_audit.jsonl``, ``supervisor_events.jsonl``
    and any ``*trace*.json`` / ``*alerts.jsonl`` inside it); the explicit
    path arguments add to / override them.  A FLEET run dir — immediate
    subdirectories each holding a replica's ``scalars.jsonl`` /
    ``serving_stats.jsonl`` — is auto-discovered: per-replica scalars
    merge through :mod:`~.aggregate` (counters/histograms sum, so the
    fleet histogram is the histogram of every replica's samples),
    serving stats concatenate, and a top-level ``router_stats.jsonl``
    rolls into the fleet section."""
    scalar_paths = list(scalar_paths)
    timeline_paths = list(timeline_paths)
    trace_paths = list(trace_paths)
    alerts_paths = list(alerts_paths)
    perf_paths = list(perf_paths)
    autopilot_paths = list(autopilot_paths)
    weights_paths = list(weights_paths)
    serving_stats_paths = ([serving_stats_path]
                           if serving_stats_path else [])
    fleet_scalar_streams: List[List[dict]] = []
    fleet_replicas: List[str] = []
    if run_dir:
        from neuronx_distributed_tpu.obs.aggregate import (
            discover_replica_dirs,
        )

        for label, sub in discover_replica_dirs(run_dir):
            fleet_replicas.append(label)
            q = os.path.join(sub, SCALARS_FILE)
            if os.path.exists(q):
                fleet_scalar_streams.append(_read_scalar_file(q))
            q = os.path.join(sub, SERVING_STATS_FILE)
            if os.path.exists(q) and q not in serving_stats_paths:
                serving_stats_paths.append(q)
            for q in sorted(glob.glob(os.path.join(sub, "*alerts.jsonl"))):
                if q not in alerts_paths:
                    alerts_paths.append(q)
            for q in sorted(glob.glob(
                    os.path.join(sub, f"*{TRACE_EVENTS_FILE}"))):
                if q not in trace_paths:
                    trace_paths.append(q)
            for q in sorted(glob.glob(
                    os.path.join(sub, f"*{PERF_ATTRIBUTION_FILE}"))):
                if q not in perf_paths:
                    perf_paths.append(q)
            for q in sorted(glob.glob(
                    os.path.join(sub, f"*{WEIGHT_SWAPS_FILE}"))):
                if q not in weights_paths:
                    weights_paths.append(q)
        if router_stats_path is None:
            q = os.path.join(run_dir, ROUTER_STATS_FILE)
            router_stats_path = q if os.path.exists(q) else None
        for q in sorted(glob.glob(os.path.join(run_dir, "*alerts.jsonl"))):
            if q not in alerts_paths:
                alerts_paths.append(q)
        for q in sorted(glob.glob(
                os.path.join(run_dir, f"*{AUTOPILOT_ACTIONS_FILE}"))):
            if q not in autopilot_paths:
                autopilot_paths.append(q)
        for q in sorted(glob.glob(
                os.path.join(run_dir, f"*{WEIGHT_SWAPS_FILE}"))):
            if q not in weights_paths:
                weights_paths.append(q)
        p = os.path.join(run_dir, SCALARS_FILE)
        if os.path.exists(p) and p not in scalar_paths:
            scalar_paths.append(p)
        if flight_path is None:
            q = os.path.join(run_dir, FLIGHT_FILE)
            flight_path = q if os.path.exists(q) else None
        if hlo_audit_path is None:
            q = os.path.join(run_dir, HLO_AUDIT_FILE)
            hlo_audit_path = q if os.path.exists(q) else None
        if supervisor_events_path is None:
            q = os.path.join(run_dir, SUPERVISOR_EVENTS_FILE)
            supervisor_events_path = q if os.path.exists(q) else None
        for q in sorted(glob.glob(os.path.join(run_dir, "*trace*.json"))):
            if q not in timeline_paths:
                timeline_paths.append(q)
        for q in sorted(glob.glob(
                os.path.join(run_dir, f"*{TRACE_EVENTS_FILE}"))):
            if q not in trace_paths:
                trace_paths.append(q)
        if serving_stats_path is None:
            q = os.path.join(run_dir, SERVING_STATS_FILE)
            serving_stats_path = q if os.path.exists(q) else None
        if serving_stats_path and serving_stats_path \
                not in serving_stats_paths:
            serving_stats_paths.append(serving_stats_path)
        if compile_ledger_path is None:
            q = os.path.join(run_dir, COMPILE_LEDGER_FILE)
            compile_ledger_path = q if os.path.exists(q) else None
        if memory_breakdown_path is None:
            q = os.path.join(run_dir, MEMORY_BREAKDOWN_FILE)
            memory_breakdown_path = q if os.path.exists(q) else None
        for q in sorted(glob.glob(
                os.path.join(run_dir, f"*{PERF_ATTRIBUTION_FILE}"))):
            if q not in perf_paths:
                perf_paths.append(q)

    scalar_records: List[dict] = []
    for p in scalar_paths:
        scalar_records.extend(_read_scalar_file(p))
    if fleet_scalar_streams:
        # per-replica streams merge into ONE synthetic stream (counters +
        # histogram buckets sum across replicas) — concatenating the raw
        # streams would let one replica's latest snapshot shadow the rest
        from neuronx_distributed_tpu.obs.aggregate import (
            merge_scalar_records,
        )

        scalar_records.extend(merge_scalar_records(fleet_scalar_streams))

    flight = None
    if flight_path and os.path.exists(flight_path):
        flight_doc = read_flight(flight_path)
        flight = {
            "reason": flight_doc["reason"],
            "dumped_at": flight_doc["dumped_at"],
            "steps_recorded": flight_doc["steps_recorded"],
            "num_records": len(flight_doc["records"]),
            "tail": flight_doc["records"][-tail:],
            "warnings": flight_doc["warnings"],
        }

    audits = read_audits(hlo_audit_path) if (
        hlo_audit_path and os.path.exists(hlo_audit_path)) else []

    supervisor = None
    if supervisor_events_path and os.path.exists(supervisor_events_path):
        supervisor = _summarize_supervisor(supervisor_events_path)

    anomalies = list(flight["warnings"]) if flight else []
    histograms = read_histograms(scalar_records)
    host_blocked = _summarize_host_blocked(histograms)
    scalars = _summarize_scalars(scalar_records, frozenset(histograms))
    kvcache = _summarize_kvcache(scalars)
    speculative = _summarize_speculative(scalars)
    fleet = _summarize_fleet(scalars)
    tenancy = _summarize_tenancy(scalars)
    slo = _summarize_slo(scalars, histograms)
    if len(serving_stats_paths) > 1:
        from neuronx_distributed_tpu.obs.aggregate import merge_serving_stats

        stats_records = merge_serving_stats(serving_stats_paths)
    else:
        stats_records = (read_serving_stats(serving_stats_paths[0])
                         if serving_stats_paths
                         and os.path.exists(serving_stats_paths[0]) else [])
    trace = summarize_trace(trace_paths, stats_records)
    alerts_section = summarize_alerts(alerts_paths)
    autopilot_section = summarize_autopilot(autopilot_paths)
    weights_section = summarize_weights(weights_paths)
    if router_stats_path:
        from neuronx_distributed_tpu.obs.aggregate import (
            summarize_router_stats,
        )

        router_stats = summarize_router_stats(router_stats_path)
    else:
        router_stats = None
    if router_stats is not None and fleet is not None:
        fleet = {**fleet, "router_stats": router_stats}
    elif router_stats is not None:
        fleet = {"router_stats": router_stats}
    ledger_records = (read_compile_ledger(compile_ledger_path)
                      if compile_ledger_path
                      and os.path.exists(compile_ledger_path) else [])
    compile_section = _summarize_compile(scalars, ledger_records, histograms)
    breakdown = (read_memory_breakdown(memory_breakdown_path)
                 if memory_breakdown_path
                 and os.path.exists(memory_breakdown_path) else None)
    memory_section = _summarize_memory(scalars, breakdown)
    # fleet runs: per-replica attribution streams merge additively
    # (device-time, flops and bytes sum; the rollup is rebuilt)
    from neuronx_distributed_tpu.obs.aggregate import merge_perf_files

    perf_section = summarize_perf(merge_perf_files(perf_paths))
    report = {
        "schema": OBS_REPORT_SCHEMA,
        "generated_at": time.time(),
        "run_dir": run_dir,
        "sources": {
            "scalars": scalar_paths,
            "flight": flight_path,
            "hlo_audit": hlo_audit_path,
            "timelines": timeline_paths,
            "supervisor_events": supervisor_events_path,
            "traces": trace_paths,
            "serving_stats": serving_stats_paths,
            "compile_ledger": compile_ledger_path,
            "memory_breakdown": memory_breakdown_path,
            "alerts": alerts_paths,
            "router_stats": router_stats_path,
            "perf": perf_paths,
            "autopilot": autopilot_paths,
            "weights": weights_paths,
            "fleet_replicas": fleet_replicas,
        },
        "scalars": scalars,
        "histograms": histograms,
        "flight": flight,
        "anomalies": anomalies,
        "hlo_audits": audits,
        "timeline": _summarize_timeline(timeline_paths),
        "supervisor": supervisor,
        "trace": trace,
        "compile": compile_section,
        "memory": memory_section,
        "alerts": alerts_section,
        "autopilot": autopilot_section,
        "weights": weights_section,
        "perf": perf_section,
        "health": {
            "anomaly_count": len(anomalies),
            "host_blocked": host_blocked,
            "kvcache": kvcache,
            "speculative": speculative,
            "fleet": fleet,
            "tenancy": tenancy,
            "slo": slo,
            # slim rollups only — the full per-family/per-subsystem tables
            # live once, at the top-level "compile"/"memory" sections
            "compile": (None if compile_section is None else {
                "compiles": compile_section["compiles"],
                "storms": compile_section["storms"],
                "thrash_warnings": compile_section["thrash_warnings"]}),
            "memory": (None if memory_section is None else {
                "total_bytes": memory_section["total_bytes"],
                "peak_total_bytes": memory_section["peak_total_bytes"]}),
            # slim alerts rollup — the full per-rule table lives once, at
            # the top-level "alerts" section
            "alerts": (None if alerts_section is None else {
                "firing": alerts_section["firing"],
                "worst_severity": alerts_section["worst_severity"],
                "rules_fired": sum(
                    1 for agg in alerts_section["rules"].values()
                    if agg["fired"])}),
            # slim autopilot rollup — the full action table lives once,
            # at the top-level "autopilot" section
            "autopilot": (None if autopilot_section is None else {
                "actions": autopilot_section["actions"],
                "rate_per_s": autopilot_section["rate_per_s"],
                "last_action": (autopilot_section["last"]["action"]
                                if autopilot_section["last"] else None)}),
            # slim weights rollup — the full per-replica version table
            # lives once, at the top-level "weights" section
            "weights": (None if weights_section is None else {
                "swaps": weights_section["swaps"],
                "failures": weights_section["failures"],
                "monotonic": weights_section["monotonic"]}),
            # slim perf rollup — the full per-family roofline table lives
            # once, at the top-level "perf" section
            "perf": (None if perf_section is None
                     or perf_section.get("rollup") is None else {
                         "mfu": perf_section["rollup"]["mfu"],
                         "mbu": perf_section["rollup"]["mbu"],
                         "pct_roofline":
                             perf_section["rollup"]["pct_roofline"],
                         "bound": perf_section["rollup"]["bound"]}),
            "total_collective_count": sum(
                a.get("total_collective_count", 0) for a in audits),
            "total_collective_bytes": sum(
                a.get("total_collective_bytes", 0) for a in audits),
            "restarts": supervisor["restarts"] if supervisor else 0,
        },
    }
    return report


def render_markdown(report: dict) -> str:
    """Human-readable rendering of :func:`build_report` output."""
    lines = ["# Run report", ""]
    h = report["health"]
    alerts = report.get("alerts")
    if alerts:
        worst = alerts["worst_severity"] or "none"
        fired = sum(agg["fired"] for agg in alerts["rules"].values())
        lines.append(
            f"- alerts: **{alerts['firing']} firing** (worst severity "
            f"{worst}); {fired} firing edge(s) across "
            f"{len(alerts['rules'])} rule(s)")
    ap = report.get("autopilot")
    if ap:
        rate = (f"{ap['rate_per_s'] * 60.0:.2f}/min"
                if ap["rate_per_s"] is not None else "n/a")
        last = (f"; last `{ap['last']['action']}` on "
                f"`{ap['last']['trigger']}`" if ap["last"] else "")
        lines.append(
            f"- autopilot: **{ap['actions']} action(s)** across "
            f"{len(ap['triggers'])} trigger(s) "
            f"(rate {rate} over {ap['span_s']:.1f}s){last}")
    wt = report.get("weights")
    if wt:
        mono = ("monotonic" if wt["monotonic"]
                else "**NON-MONOTONIC version order**")
        ver = (f"; now at version {wt['last']['version']} "
               f"({wt['last']['source']})" if wt["last"] else "")
        ms = (f", {wt['swap_ms_mean']:.1f} ms mean swap"
              if wt["swap_ms_mean"] is not None else "")
        lines.append(
            f"- weights: **{wt['swaps']} live swap(s)**, "
            f"{wt['failures']} failure(s) across "
            f"{len(wt['replicas'])} engine(s) ({mono}{ms}){ver}")
    lines.append(f"- anomalies: **{h['anomaly_count']}**")
    lines.append(f"- supervisor restarts: **{h.get('restarts', 0)}**")
    lines.append(f"- collectives across audited programs: "
                 f"{h['total_collective_count']} ops, "
                 f"{h['total_collective_bytes']:,} bytes")
    for sys_name, hb in sorted(h.get("host_blocked", {}).items()):
        frac = f", {hb['frac']:.1%} of step time" if "frac" in hb else ""
        lines.append(
            f"- {sys_name} host-blocked: {hb['blocked_ms_total']:.1f} ms "
            f"across {hb['fetches']:.0f} fetches{frac}")
    kv = h.get("kvcache")
    if kv:
        hit = (f"{kv['prefix_hit_rate']:.1%} prefix hit rate "
               f"({kv['prefix_hits']:.0f}/{kv['prefix_hits'] + kv['prefix_misses']:.0f} pages)"
               if kv["prefix_hit_rate"] is not None else "no prefix lookups")
        gather = (f"{kv.get('gather_bytes', 0.0):,.0f} gather-path bytes"
                  if kv.get("gather_bytes") else
                  "0 gather-path bytes (kernel decode)")
        lines.append(
            f"- kv cache: {kv['pages_in_use']:.0f}/{kv['pages_total']:.0f} "
            f"pages in use ({kv['occupancy']:.1%}, "
            f"{kv['pages_cached']:.0f} held by the prefix cache); {hit}; "
            f"{kv['prefills_skipped']:.0f} prefills skipped, "
            f"{kv['evictions']:.0f} evictions, "
            f"{kv['cow_copies']:.0f} cow copies; {gather}")
    fleet = h.get("fleet")
    if fleet and "router_stats" in fleet and fleet["router_stats"]:
        rstats = fleet["router_stats"]
        states = ", ".join(f"{k} {v}" for k, v in rstats["by_state"].items())
        lines.append(
            f"- router stats: {rstats['records']} terminal record(s) "
            f"({states}); {rstats['requeued']} survived a failover across "
            f"replicas {rstats['replicas_seen']}")
    if fleet and "dispatched" in fleet:
        aff = (f"{fleet['affinity_hit_rate']:.1%} affinity hits "
               f"({fleet['affinity_hits']:.0f}/"
               f"{fleet['affinity_hits'] + fleet['affinity_misses']:.0f})"
               if fleet["affinity_hit_rate"] is not None
               else "no fingerprinted dispatches")
        pool = (f", pool prefix hit rate {fleet['fleet_prefix_hit_rate']:.1%}"
                if fleet["fleet_prefix_hit_rate"] is not None else "")
        lines.append(
            f"- fleet: {fleet['replicas_alive']:.0f} replica(s) in rotation; "
            f"{fleet['dispatched']:.0f} dispatches, "
            f"{fleet['requeued']:.0f} requeued over "
            f"{fleet['failovers']:.0f} failover(s) "
            f"({fleet['restarts']:.0f} restarts, "
            f"{fleet['retired']:.0f} retired); {aff}{pool}")
    ten = h.get("tenancy")
    if ten:
        hit = (f"{ten['adapter_hit_rate']:.1%} adapter hit rate "
               f"({ten['adapter_hits']:.0f} hits/"
               f"{ten['adapter_loads']:.0f} loads)"
               if ten["adapter_hit_rate"] is not None else "no adapter pins")
        quant = (f"; {ten['quant_pages']:.0f} int8 page writes"
                 if ten["quant_pages"] else "")
        lines.append(
            f"- tenancy: {ten['adapters_resident']:.0f} adapter(s) resident "
            f"({ten['adapter_pool_pages_in_use']:.0f} pool pages); {hit}; "
            f"{ten['adapter_evictions']:.0f} evictions{quant}")
    slo = h.get("slo")
    if slo:
        parts = []
        for cls, c in sorted(slo.get("classes", {}).items()):
            tt = (f"ttft p99 ~{c['ttft_p99_ms']:.0f}ms"
                  if c["ttft_p99_ms"] is not None else "ttft p99 n/a")
            it = (f"inter-token p99 ~{c['intertoken_p99_ms']:.0f}ms"
                  if c["intertoken_p99_ms"] is not None
                  else "inter-token p99 n/a")
            parts.append(f"{cls}: {tt}, {it}")
        tail = ("; ".join(parts)) if parts else "no per-class latencies"
        lines.append(
            f"- slo: {slo['preemptions']:.0f} preemption(s), "
            f"{slo['shed']:.0f} shed at admission, "
            f"{slo['expired_before_prefill']:.0f} expired pre-prefill, "
            f"{slo['prefill_chunks']:.0f} prefill chunk(s); {tail}")
    spec = h.get("speculative")
    if spec:
        rate = (f"{spec['acceptance_rate']:.1%} acceptance"
                if spec["acceptance_rate"] is not None else "no proposals")
        tps = (f"{spec['tokens_per_round']:.2f} tokens/step"
               if spec["tokens_per_round"] is not None else "no rounds")
        lines.append(
            f"- speculative: {tps} over {spec['rounds']:.0f} rounds; {rate} "
            f"({spec['accepted']:.0f}/{spec['proposed']:.0f} draft tokens)")
    comp = report.get("compile")
    if comp:
        cache = comp.get("cache") or {}
        hit = (f"{cache['hit_rate']:.1%} cache hit rate"
               if cache.get("hit_rate") is not None else "no cache lookups")
        lines.append(
            f"- compile: {comp['compiles']:.0f} compile(s) "
            f"({comp.get('cold_ms_total', 0):,.0f} ms total wall); "
            f"**{comp['storms']:.0f} storm(s)** after warmup, "
            f"{comp['thrash_warnings']:.0f} thrash warning(s), "
            f"{comp.get('evictions', 0):.0f} eviction(s); {hit}")
    perf = report.get("perf")
    if perf and perf.get("rollup"):
        roll = perf["rollup"]
        ceiling = (f"; tokens/s ceiling {roll['toks_per_s_ceiling']:,.0f}"
                   if roll.get("toks_per_s_ceiling") else "")
        lines.append(
            f"- perf: MFU {roll['mfu']:.1%}, MBU {roll['mbu']:.1%}, "
            f"{roll['pct_roofline']:.1%} of roofline "
            f"({roll['bound']}-bound on {perf['device']}){ceiling}")
    memh = report.get("memory")
    if memh:
        top = ", ".join(f"{name} {nbytes / 2**20:,.1f}MiB"
                        for name, nbytes in memh.get("top", [])[:3])
        dev = memh.get("device") or {}
        used = dev.get("device_bytes_in_use")
        device = (f"; device {used / 2**20:,.1f}MiB in use"
                  if used is not None else "")
        lines.append(
            f"- memory: {memh['total_bytes'] / 2**20:,.1f} MiB accounted "
            f"(peak {memh['peak_total_bytes'] / 2**20:,.1f} MiB); "
            f"top holders: {top or 'none'}{device}")
    lines.append("")

    sup = report.get("supervisor")
    if sup:
        lines += ["## Supervisor", "",
                  f"{sup['attempts']} attempt(s), {sup['restarts']} "
                  f"restart(s); "
                  + ("succeeded" if sup["succeeded"] else
                     ("gave up" if sup["gave_up"] else
                      f"final rc {sup['final_rc']}"))]
        if sup["crash_causes"]:
            lines.append(f"- crash causes: {', '.join(sup['crash_causes'])}")
        if sup["mean_recover_s"] is not None:
            lines.append(f"- time to recover: mean {sup['mean_recover_s']}s "
                         f"({sup['recover_s']})")
        lines.append("")

    if report["scalars"]:
        lines += ["## Step metrics", "",
                  "| tag | count | last | min | max | mean |",
                  "|---|---|---|---|---|---|"]
        for tag, s in sorted(report["scalars"].items()):
            lines.append(
                f"| {tag} | {s['count']} | {s['last']:.6g} | {s['min']:.6g} "
                f"| {s['max']:.6g} | {s['mean']:.6g} |")
        lines.append("")

    if report["histograms"]:
        lines += ["## Histograms", ""]
        for name, hist in sorted(report["histograms"].items()):
            lines.append(f"### {name}")
            lines.append(f"count {hist['count']:.0f}, sum {hist['sum']:.6g}, "
                         f"mean {hist['mean']:.6g}")
            lines.append("")
            lines.append("| le | cumulative |")
            lines.append("|---|---|")
            for le, cum in hist["buckets"].items():
                lines.append(f"| {le} | {cum:.0f} |")
            lines.append("")

    if report["flight"]:
        fl = report["flight"]
        lines += ["## Flight recorder", "",
                  f"dump reason `{fl['reason']}`, {fl['num_records']} records "
                  f"held of {fl['steps_recorded']} steps recorded", ""]
        for rec in fl["tail"]:
            lines.append(f"- step {rec['step']}: " + ", ".join(
                f"{k}={v:.6g}" if isinstance(v, float) else f"{k}={v}"
                for k, v in rec.items() if k not in ("step", "time")))
        lines.append("")

    alerts = report.get("alerts")
    if alerts and alerts["rules"]:
        lines += ["## Alerts", "",
                  "| rule | severity | fired | resolved | firing | "
                  "time firing (s) |",
                  "|---|---|---|---|---|---|"]
        for name, agg in sorted(
                alerts["rules"].items(),
                key=lambda kv: -kv[1]["time_firing_s"]):
            lines.append(
                f"| {name} | {agg['severity']} | {agg['fired']} | "
                f"{agg['resolved']} | {agg['firing']} | "
                f"{agg['time_firing_s']:.3f} |")
        lines.append("")

    ap = report.get("autopilot")
    if ap and ap["actions"]:
        lines += ["## Autopilot actions", "",
                  "| mono | action | trigger | replica | mode | "
                  "budget left |",
                  "|---|---|---|---|---|---|"]
        for r in ap["tail"]:
            lines.append(
                f"| {r['mono']:.3f} | {r['action']} | {r['trigger']} | "
                f"{r['replica'] if r['replica'] >= 0 else '-'} | "
                f"{r['mode']} | {r['budget_remaining']} |")
        lines += ["", "Per-trigger rollup:", "",
                  "| trigger | actions | by action | replicas |",
                  "|---|---|---|---|"]
        for name, trig in ap["triggers"].items():
            by = ", ".join(f"{k} {v}" for k, v in trig["by_action"].items())
            reps = ",".join(str(r) for r in trig["replicas"]) or "-"
            lines.append(
                f"| {name} | {trig['actions']} | {by} | {reps} |")
        lines.append("")
    elif ap:
        lines += ["## Autopilot actions", "",
                  "Autopilot was on and never had to act.", ""]

    if report["anomalies"]:
        lines += ["## Anomalies", ""]
        for w in report["anomalies"]:
            lines.append(f"- step {w['step']} [{w['detector']}]: {w['message']}")
        lines.append("")

    if report["hlo_audits"]:
        lines += ["## HLO communication audits", ""]
        for a in report["hlo_audits"]:
            counts = {k: v for k, v in a["collective_counts"].items() if v}
            lines.append(
                f"- `{a['name']}`: {counts or 'no collectives'}; "
                f"{a['total_collective_bytes']:,} bytes")
        lines.append("")

    comp = report.get("compile")
    if comp and comp.get("families"):
        lines += ["## Compile ledger", "",
                  "| family | compiles | cold ms | distinct keys | "
                  "evictions |",
                  "|---|---|---|---|---|"]
        for name, f in sorted(comp["families"].items()):
            lines.append(
                f"| {name} | {f['compiles']} | {f['cold_ms']:.1f} | "
                f"{f['distinct_keys']} | {f['evictions']} |")
        lines.append("")

    perf = report.get("perf")
    if perf and perf.get("families"):
        lines += [f"## Roofline attribution ({perf['device']})", "",
                  "| family | calls | device ms | intensity | bound | "
                  "% roofline | MFU | MBU |",
                  "|---|---|---|---|---|---|---|---|"]
        for name, f in sorted(perf["families"].items(),
                              key=lambda kv: -kv[1]["device_ms"]):
            ai = (f"{f['arithmetic_intensity']:.1f}"
                  if f["arithmetic_intensity"] is not None else "n/a")
            lines.append(
                f"| {name} | {f['calls']:.0f} | {f['device_ms']:.1f} | "
                f"{ai} | {f['bound']} | {f['pct_roofline']:.1%} | "
                f"{f['mfu']:.1%} | {f['mbu']:.1%} |")
        if perf.get("top_time_eaters"):
            lines += ["", "Top time-eaters: "
                      + ", ".join(perf["top_time_eaters"])]
        lines.append("")

    memr = report.get("memory")
    if memr and memr.get("subsystems"):
        lines += ["## Memory ledger", "",
                  "| subsystem | bytes | peak bytes |",
                  "|---|---|---|"]
        for name, s in sorted(memr["subsystems"].items()):
            lines.append(f"| {name} | {s.get('bytes', 0):,.0f} | "
                         f"{s.get('peak_bytes', 0):,.0f} |")
        lines.append("")

    trace = report.get("trace")
    if trace:
        lines += ["## Request traces", "",
                  f"{trace['spans']} spans across {trace['requests']} "
                  f"request(s) ({trace['files']} trace file(s)); aggregate "
                  "phase time: "
                  + ", ".join(f"{k} {v:.1f} ms"
                              for k, v in trace["by_phase_ms"].items()), ""]
        if trace["slowest"]:
            lines += ["Slowest requests (per-request waterfall):", "",
                      "| request | state | total ms | queue | prefill | "
                      "decode | preempted | migrate | hops | replicas |",
                      "|---|---|---|---|---|---|---|---|---|---|"]
            for e in trace["slowest"]:
                check = (f" (stats {e['stats_total_ms']:.1f})"
                         if e.get("stats_total_ms") is not None else "")
                lines.append(
                    f"| {e['request_id']} | {e['state'] or '?'} | "
                    f"{e['total_ms']:.1f}{check} | {e['queue_ms']:.1f} | "
                    f"{e['prefill_ms']:.1f} | {e['decode_ms']:.1f} | "
                    f"{e['preempted_ms']:.1f} | "
                    f"{e.get('migrate_ms', 0.0):.1f} | {e['hops']} | "
                    f"{','.join(str(r) for r in e['replicas'])} |")
            lines.append("")

    tl = report["timeline"]
    if tl["events"] or tl["instants"]:
        lines += ["## Timeline", "",
                  f"{tl['events']} events, {tl['instants']} instants "
                  f"across {tl['files']} file(s)"]
        for name, ms in tl["total_ms_by_name"].items():
            lines.append(f"- {name}: {ms:.1f} ms total")
        lines.append("")
    return "\n".join(lines)
