"""Transfer audit: make the hot paths' no-sync invariant enforceable.

The async hot paths (``fit(prefetch=..., defer_metrics=...)`` and the
serving engine's pipelined decode) promise a transfer discipline: inside a
steady-state step, every host↔device crossing is *explicit* — batches enter
through :class:`~..data.prefetch.DevicePrefetcher`'s staged ``device_put``,
scalars leave through one packed :meth:`TransferAudit.fetch` — and nothing
crosses implicitly (a stray ``float(arr)`` / ``np.asarray(arr)`` /
``jit(numpy_arg)`` is a full device drain on a TPU).  This module turns that
promise from aspiration into a checked contract:

- :meth:`TransferAudit.section` wraps a hot region in ``jax.transfer_guard``
  — ``mode="forbid"`` makes any *implicit* transfer raise (tests run this
  way; production can too), while explicit ``device_put``/``device_get``
  stay allowed;
- :meth:`TransferAudit.fetch` / :meth:`TransferAudit.put` are the sanctioned
  explicit crossings: they count into the registry
  (``transfer/explicit_fetches_total`` / ``transfer/explicit_puts_total``)
  and time how long the host was blocked waiting on the device
  (``transfer/fetch_wait_ms`` plus a per-subsystem
  ``<label>/host_blocked_ms`` histogram) — so "one packed fetch per step"
  is assertable from metrics, and ``host_blocked_frac`` is derivable from
  artifacts alone.

Backend caveat (why ``forbid`` + counting, not counting alone): XLA's
transfer guard fires for host→device transfers on every backend, but
device→host reads of CPU-backed arrays are zero-copy and never trip it —
so on the CPU test mesh the d2h side of the invariant is enforced by
accounting (exactly N explicit fetches, none elsewhere) while h2d is
enforced by the real guard; on TPU ``forbid`` enforces both for real.
"""

from __future__ import annotations

import contextlib
import time
from typing import Any, Optional

import jax

from neuronx_distributed_tpu.utils.logger import get_logger

logger = get_logger(__name__)

MODES = ("off", "observe", "forbid")

# metric names (the obs.schemas.REGISTRY_METRICS contract)
FETCHES_TOTAL = "transfer/explicit_fetches_total"
PUTS_TOTAL = "transfer/explicit_puts_total"
FETCH_WAIT_MS = "transfer/fetch_wait_ms"
GUARDED_SECTIONS_TOTAL = "transfer/guarded_sections_total"


class TransferAudit:
    """Per-run transfer accountant + optional transfer-guard enforcer.

    ``registry`` (an ``obs.MetricRegistry``) receives the counters and
    host-blocked histograms; ``None`` keeps the audit free (time is still
    accumulated on :attr:`blocked_s` for callers like ``bench.py`` that
    report a fraction directly).  ``mode``:

    - ``"off"``: :meth:`section` is a no-op (fetch/put still count);
    - ``"observe"``: sections are counted but transfers are not restricted;
    - ``"forbid"``: sections run under ``jax.transfer_guard("disallow")`` —
      an implicit transfer inside raises ``XlaRuntimeError`` naming the
      offending aval, explicit ``device_put``/``device_get`` pass.
    """

    def __init__(self, registry: Any = None, mode: str = "observe"):
        if mode not in MODES:
            raise ValueError(f"mode must be one of {MODES}, got {mode!r}")
        self.registry = registry
        self.mode = mode
        self.blocked_s = 0.0   # cumulative host time spent inside fetch()
        self.fetches = 0
        self.puts = 0
        if registry is not None:
            from neuronx_distributed_tpu.obs import MS_BUCKETS

            self._ms_buckets = MS_BUCKETS
            registry.counter(FETCHES_TOTAL)
            registry.counter(PUTS_TOTAL)
            registry.counter(GUARDED_SECTIONS_TOTAL)
            registry.histogram(FETCH_WAIT_MS, MS_BUCKETS)

    @contextlib.contextmanager
    def section(self, name: str):
        """Enter a guarded hot section.  In ``forbid`` mode an implicit
        host↔device transfer inside raises; the section counter ticks in
        every mode but ``off`` so dashboards can see coverage."""
        if self.mode == "off":
            yield
            return
        if self.registry is not None:
            self.registry.counter(GUARDED_SECTIONS_TOTAL).inc()
        if self.mode == "forbid":
            with jax.transfer_guard("disallow"):
                yield
        else:
            yield

    def fetch(self, tree: Any, label: Optional[str] = None) -> Any:
        """THE sanctioned device→host read: one explicit ``jax.device_get``
        of (ideally packed) ``tree``.  Counts the fetch and observes the
        host-blocked wait into ``transfer/fetch_wait_ms`` and, when
        ``label`` is given, ``<label>/host_blocked_ms`` — one histogram per
        subsystem (``train``/``serving``) so overlap wins are graphable."""
        t0 = time.perf_counter()
        out = jax.device_get(tree)
        wait_s = time.perf_counter() - t0
        self.blocked_s += wait_s
        self.fetches += 1
        if self.registry is not None:
            self.registry.counter(FETCHES_TOTAL).inc()
            self.registry.histogram(
                FETCH_WAIT_MS, self._ms_buckets).observe(wait_s * 1e3)
            if label is not None:
                self.registry.histogram(
                    f"{label}/host_blocked_ms",
                    self._ms_buckets).observe(wait_s * 1e3)
        return out

    def put(self, tree: Any, shardings: Any = None) -> Any:
        """The sanctioned host→device write: explicit ``jax.device_put``
        (legal inside a ``forbid`` section, unlike handing numpy straight to
        a jitted call)."""
        out = (jax.device_put(tree) if shardings is None
               else jax.device_put(tree, shardings))
        self.puts += 1
        if self.registry is not None:
            self.registry.counter(PUTS_TOTAL).inc()
        return out
