"""Checked-in schemas for every JSONL/JSON artifact the framework emits.

Downstream tooling (``tools/obs_report.py``, dashboards, the judge reading
``docs/tpu_watch_results.jsonl``) parses these files; this module is the
contract that keeps the formats stable.  A schema here is deliberately a
floor, not a straitjacket: records may carry EXTRA keys (forward-compatible
growth), but the required keys and their types may never change without a
schema-version bump.  ``tests/test_artifact_schemas.py`` is the smoke test
that re-validates every emitter against this list.
"""

from __future__ import annotations

import json
from typing import Any, Dict, Iterable

_NUM = (int, float)

# kind -> {field: type-or-tuple-of-types}; every field is required, extra
# fields are allowed.
SCHEMAS: Dict[str, Dict[str, Any]] = {
    # one line of scalars.jsonl — written by trainer.scalar_log.ScalarWriter
    # AND obs.registry.MetricRegistry.dump_jsonl
    "scalars": {"step": int, "tag": str, "value": _NUM, "time": _NUM},
    # flight_record.json top-level document (obs.flight.FlightRecorder.dump)
    "flight_record": {
        "schema": str, "reason": str, "dumped_at": _NUM, "capacity": int,
        "steps_recorded": int, "records": list, "warnings": list,
    },
    # one entry of flight_record.json["records"]
    "flight_step": {"step": int, "time": _NUM},
    # one entry of flight_record.json["warnings"] (anomaly detectors)
    "anomaly": {"step": int, "detector": str, "message": str, "time": _NUM},
    # one line of hlo_audit.jsonl (obs.hlo_audit.comm_audit)
    "hlo_audit": {
        "schema": str, "name": str, "time": _NUM,
        "collective_counts": dict, "collective_bytes": dict,
        "total_collective_count": int, "total_collective_bytes": int,
    },
    # one line of docs/tpu_watch_results.jsonl (tools/tpu_watch.py append)
    "tpu_watch": {"ts": str, "kind": str},
    # one line of trace_events.jsonl (obs.tracing.Tracer.export_jsonl) —
    # one record per finished span: the request-lifecycle distributed
    # trace.  request_id is the fleet-global id (-1 for batch-level spans
    # like one engine decode step), replica the producing replica (-1
    # off-fleet), parent_id the enclosing span (null at a trace root).
    # Every span carries BOTH clocks: ts (wall, shared epoch) and mono
    # (monotonic start == t_start; t_start/t_end are the span's interval
    # on the monotonic clock) so cross-replica merges sort correctly
    # under wall-clock skew.  attrs is free-form span detail (phase
    # boundaries, token ranges, hop counts, ...).
    "trace_event": {
        "schema": str, "name": str, "span_id": int,
        "parent_id": (int, type(None)), "request_id": int, "replica": int,
        "t_start": _NUM, "t_end": _NUM, "ts": _NUM, "mono": _NUM,
        "attrs": dict,
    },
    # one line of serving_stats.jsonl (serving.engine.ServingEngine) —
    # one record per TERMINAL request; ttft_ms is null for requests that
    # never produced a token (cancelled/timed out while queued).  v2 adds
    # the speculative-decoding accounting: draft tokens proposed/accepted
    # for the request and its acceptance rate (null when the engine never
    # speculated for it — including every non-spec engine).  v3 adds the
    # tenancy accounting: which LoRA adapter served the request (0 = the
    # base model — every request off multi-adapter mode).  v4 adds the SLO
    # scheduling accounting: the priority class, the deadline budget (null
    # = none), the queue wait, how many times a higher tier preempted the
    # request's slot, and — for requests the engine shed before prefill —
    # the shed reason (null otherwise)
    "serving_stats": {
        "schema": str, "time": _NUM, "request_id": int, "state": str,
        "finish_reason": (str, type(None)), "prompt_len": int,
        "new_tokens": int, "queue_ms": _NUM,
        "ttft_ms": (int, float, type(None)), "total_ms": _NUM,
        "spec_proposed": int, "spec_accepted": int,
        "acceptance_rate": (int, float, type(None)),
        "adapter_id": int,
        "priority": str,
        "deadline_s": (int, float, type(None)),
        "queue_wait_ms": _NUM,
        "preemptions": int,
        "shed_reason": (str, type(None)),
        # v5 (tracing PR): second monotonic stamp pairing the wall `time`,
        # per-request work decomposition, and the trace_events.jsonl
        # linkage (null when the engine ran without a tracer).  v4 records
        # lack these five fields; obs.report reads them with defaults.
        "mono": _NUM,
        "decode_steps": int,
        "prefill_chunks": int,
        "preempted_ms": _NUM,
        "trace_id": (int, type(None)),
        # v6 (live-weights PR): the weights_version whose params decoded
        # the request's LAST committed token (0 = the process-start
        # weights, never swapped) — a mid-swap request's output is
        # attributable to the version that actually produced it.  v5
        # records lack the field; obs.report reads it with default 0.
        "weights_version": int,
    },
    # one line of router_stats.jsonl (serving.fleet.router.FleetRouter) —
    # one record per TERMINAL request across the whole fleet: which replica
    # finished it, how many times it was dispatched/requeued (requeues > 0
    # means it survived a failover), how many leading prompt pages the
    # affinity shadow matched at dispatch, and the routing policy in force.
    # replica is -1 for requests that never reached an engine (router-held
    # cancellation / total capacity loss).  v2 (disagg PR) adds the
    # disaggregation evidence: migrations counts KV-page migration hops
    # (export/import moves between replica pools — distinct from requeues,
    # which re-prefill), role is the steering role of the replica that
    # finished the request ("prefill"/"decode"/"mixed"; null for
    # router-held terminals).
    "router_stats": {
        "schema": str, "time": _NUM, "request_id": int, "client_id": int,
        "replica": int, "state": str, "finish_reason": (str, type(None)),
        "dispatches": int, "requeues": int, "migrations": int,
        "role": (str, type(None)), "affinity_pages": int,
        "new_tokens": int, "policy": str,
    },
    # one line of supervisor_events.jsonl (resilience.supervisor.Supervisor)
    # — events: start / exit / restart / giveup / success; extra keys carry
    # the event payload (pid, rc, cause, backoff_s, resume_tag, ...)
    "supervisor_event": {
        "schema": str, "time": _NUM, "event": str, "attempt": int,
    },
    # one line of compile_ledger.jsonl (obs.compile_ledger.CompileLedger)
    # — events: "compile" (one program compiled: family is the program
    # family, key the shape/static key, kind "aot" | "jit", wall_ms the
    # measured compile wall time or null when only the event is known),
    # "eviction" (an LRU dropped a compiled program — key is the EVICTED
    # key, so thrash is attributable), "thrash" (a family's distinct keys
    # exceeded its cache capacity), "warmup_done".  after_warmup marks
    # compile rows recorded past declare_warmup_done — each one is a
    # compile_storm.  Compile rows may carry extra cost/memory stats
    # (flops, bytes_accessed, *_size_in_bytes, signature).
    "compile_ledger": {
        "schema": str, "time": _NUM, "mono": _NUM, "event": str,
        "family": str, "key": str, "kind": str,
        "wall_ms": (int, float, type(None)), "after_warmup": bool,
    },
    # one line of alerts.jsonl (obs.health.HealthMonitor) — one record per
    # alert EDGE (state "firing" | "resolved"; steady states are never
    # re-emitted).  rule names the rule (or externally-driven condition,
    # e.g. replica_down), window labels a burn-rate rule's window pair
    # (null for point rules), observed/bound carry the evidence at the
    # edge (null when the edge is event-driven), replica tags the emitting
    # monitor (-1 = fleet/off-fleet).  Extra keys carry rule detail
    # (duration_s on resolves, key/cause on conditions, slow_ewma, ...).
    "alert": {
        "schema": str, "time": _NUM, "mono": _NUM, "rule": str,
        "severity": str, "state": str, "window": (str, type(None)),
        "observed": (int, float, type(None)),
        "bound": (int, float, type(None)), "replica": int,
    },
    # one line of autopilot_actions.jsonl (serving.fleet.autopilot
    # .Autopilot) — one record per remediation ACTION the controller took
    # (evaluations that act on nothing emit nothing).  action is the kind
    # ("scale_out" | "scale_in" | "restart" | "tighten" | "relax" |
    # "rebalance"), trigger the alert rule (or synthetic trigger: "idle",
    # "queue_mix", "burn_resolved") that drove it, edge the triggering
    # alert's firing view (null for synthetic triggers), replica the
    # acted-on replica (-1 for fleet-wide actions like admission
    # tightening), mode the controller mode at emission ("auto" always,
    # today — page_only emits nothing), detail free-form action payload
    # (new fleet size, shed scale, target role, ...), budget_remaining
    # the global action budget left in the rolling window AFTER this
    # action — the flap-bound audit trail.
    "autopilot_action": {
        "schema": str, "time": _NUM, "mono": _NUM, "action": str,
        "trigger": str, "mode": str, "replica": int, "detail": dict,
        "edge": (dict, type(None)), "budget_remaining": int,
    },
    # one line of weight_swaps.jsonl (weights.swapper.WeightSwapper) — one
    # record per swap ATTEMPT on one engine.  event is "swap" (committed)
    # | "swap_failed" (validation / chaos / load failure — the old weights
    # kept serving); version is the monotonic weights_version the engine
    # serves AFTER the attempt (unchanged on failure), source "memory"
    # (in-process param pytree, the rollout→train→swap path) | "checkpoint"
    # (orbax round-trip), swap_ms the load+validate+install wall time
    # (null when the attempt died before the clock mattered), error the
    # failure detail (null on success), replica the owning fleet replica
    # (-1 off-fleet).
    "weight_swap": {
        "schema": str, "time": _NUM, "mono": _NUM, "event": str,
        "version": int, "source": str, "ok": bool,
        "swap_ms": (int, float, type(None)),
        "error": (str, type(None)), "replica": int,
    },
    # memory_breakdown.json (obs.memory_ledger.MemoryLedger.dump) — the
    # per-subsystem device-byte breakdown, dumped on demand and on
    # RESOURCE_EXHAUSTED (reason "oom:<ExcType>"); "top" names the biggest
    # holders, "device" the backend's memory_stats() truth when available
    "memory_breakdown": {
        "schema": str, "time": _NUM, "reason": str, "subsystems": dict,
        "total_bytes": _NUM, "peak_total_bytes": _NUM,
        "device": (dict, type(None)), "programs": dict, "top": list,
    },
    # one line of perf_attribution.jsonl (obs.perf.PerfAttribution.dump)
    # — one record per phase-fn family plus a "_total" rollup: device
    # wall-time + call counts joined with the compiled program's
    # flops/bytes against the DeviceSpec roofline.  arithmetic_intensity
    # is null when the family moved no accounted bytes (cost model blind
    # or truly zero); bound is "compute" | "memory"; pct_roofline is
    # lower_bound/achieved (1.0 = at the roofline).  The "_total" record
    # carries extra "tokens"/"toks_per_s_ceiling" keys (extras — this is
    # a floor).
    "perf_attribution": {
        "schema": str, "family": str, "calls": _NUM, "device_ms": _NUM,
        "flops": _NUM, "bytes": _NUM, "flops_per_s": _NUM,
        "bytes_per_s": _NUM,
        "arithmetic_intensity": (int, float, type(None)),
        "bound": str, "lower_bound_ms": _NUM, "pct_roofline": _NUM,
        "mfu": _NUM, "mbu": _NUM, "device": str, "peak_flops": _NUM,
        "hbm_bytes_per_s": _NUM, "time": _NUM, "mono": _NUM,
    },
    # tools/obs_report.py output document; v2 added the required "trace"
    # key (per-request waterfalls from trace_events.jsonl); v3 adds the
    # resource-ledger sections — "compile" (compile_ledger.jsonl rollup)
    # and "memory" (mem/* gauges + memory_breakdown.json), both null when
    # the run carried no ledger; v4 (fleet health PR) adds the required
    # "alerts" section (alerts.jsonl rollup: firing count, worst severity,
    # per-rule edge counts and time-firing; null when the run carried no
    # health monitor); v5 (perf attribution PR) adds the required "perf"
    # section (perf_attribution.jsonl rollup: per-family roofline table +
    # MFU/tokens-ceiling rollup; null when the run carried no perf layer);
    # v6 (autopilot PR) adds the required "autopilot" section
    # (autopilot_actions.jsonl rollup: action table, per-trigger/per-kind
    # counts, action rate; null when the run carried no autopilot); v7
    # (live-weights PR) adds the required "weights" section
    # (weight_swaps.jsonl rollup: swap/failure counts, version range,
    # swap-latency stats; null when the run never swapped weights)
    "obs_report": {
        "schema": str, "generated_at": _NUM, "scalars": dict,
        "histograms": dict, "flight": (dict, type(None)),
        "anomalies": list, "hlo_audits": list, "timeline": dict,
        "supervisor": (dict, type(None)), "trace": (dict, type(None)),
        "compile": (dict, type(None)), "memory": (dict, type(None)),
        "alerts": (dict, type(None)), "perf": (dict, type(None)),
        "autopilot": (dict, type(None)), "weights": (dict, type(None)),
    },
}


# Registry-metric contract: the async-hot-path metrics that flow into
# scalars.jsonl through MetricRegistry.to_scalar_records (histograms
# flatten to `name/count`, `name/sum` and cumulative `name/le_*` tags — all
# validating as `scalars` records).  Name -> kind; a registered metric of
# the wrong kind is an emitter bug (it would misfile the flattened tags),
# which validate_registry_metrics catches.  Extra, undeclared metrics are
# always allowed — this is a floor, like the record schemas above.
REGISTRY_METRICS: Dict[str, str] = {
    # data/prefetch.DevicePrefetcher — the staged input pipeline
    "data/prefetch_queue_depth": "gauge",
    "data/prefetch_staged_ahead": "gauge",
    "data/prefetch_rewinds_total": "counter",
    "data/prefetch_batches_staged_total": "counter",
    "data/prefetch_wait_ms": "histogram",
    # obs/transfer_audit.TransferAudit — explicit-crossing accounting
    "transfer/explicit_fetches_total": "counter",
    "transfer/explicit_puts_total": "counter",
    "transfer/fetch_wait_ms": "histogram",
    "transfer/guarded_sections_total": "counter",
    # host-blocked wall time per subsystem (fit deferred fetch / serving
    # packed decode fetch)
    "train/host_blocked_ms": "histogram",
    "serving/host_blocked_ms": "histogram",
    # kvcache/ paged-KV subsystem (serving.paged.PagedKVManager +
    # kvcache.allocator / kvcache.prefix) — pool occupancy and prefix-reuse
    # effectiveness
    "kvcache/pages_total": "gauge",
    "kvcache/pages_in_use": "gauge",
    "kvcache/pages_cached": "gauge",
    "kvcache/prefix_hits_total": "counter",
    "kvcache/prefix_misses_total": "counter",
    "kvcache/prefill_skipped_total": "counter",
    "kvcache/cow_copies_total": "counter",
    "kvcache/evictions_total": "counter",
    # paged GATHER-path decode accounting: bytes spent rematerializing the
    # contiguous [B, T] K/V views from the page pool — stays ZERO when the
    # block-table-native kernel (ops.paged_attention) serves decode
    "kvcache/gather_bytes_total": "counter",
    # KV chain transfer (kvcache.transfer, disagg PR): pages serialized
    # out of / admitted into page pools by migration and fleet-prefix
    # fills; the fleet_prefix counters split directory consultations by
    # whether a sibling's chain could be imported instead of re-prefilled
    "kvcache/pages_exported_total": "counter",
    "kvcache/pages_imported_total": "counter",
    "kvcache/fleet_prefix_hits_total": "counter",
    "kvcache/fleet_prefix_misses_total": "counter",
    # int8 KV pages (kvcache.quant): pages written through a
    # quantize-on-write path (prefill page writes + decode requant writes)
    "kvcache/quant_pages_total": "counter",
    # multi-tenant serving (tenancy.AdapterStore) — adapter-pool residency
    # and churn: hits are pure refcount bumps, loads page a cold adapter
    # in, evictions reclaim an unpinned one under pressure
    "tenancy/adapters_resident": "gauge",
    "tenancy/adapter_pool_pages_in_use": "gauge",
    "tenancy/adapter_hits_total": "counter",
    "tenancy/adapter_loads_total": "counter",
    "tenancy/adapter_evictions_total": "counter",
    # SLO serving (stall-free serving PR): preemptions counts batch-tier
    # victims parked for the interactive queue head, shed counts
    # deadline-infeasible requests rejected at submit (SLOInfeasible),
    # expired_before_prefill counts granted requests whose deadline died
    # between the sweep and their prefill/chunk dispatch, prefill_chunks
    # counts chunked-prefill dispatches; the per-class TTFT/inter-token
    # histograms carry the per-tier latency story
    "serving/preemptions_total": "counter",
    "serving/shed_total": "counter",
    "serving/expired_before_prefill_total": "counter",
    "serving/prefill_chunks_total": "counter",
    "serving/ttft_ms_interactive": "histogram",
    "serving/ttft_ms_batch": "histogram",
    "serving/intertoken_ms_interactive": "histogram",
    "serving/intertoken_ms_batch": "histogram",
    # serving speculative decoding (serving.engine draft-k-verify rounds):
    # proposed/accepted measure draft quality, committed/rounds is the
    # tokens-per-step headline
    "serving/spec_proposed_total": "counter",
    "serving/spec_accepted_total": "counter",
    "serving/spec_committed_total": "counter",
    "serving/spec_rounds_total": "counter",
    # serving fleet router (serving.fleet.router.FleetRouter) — pool-wide
    # admission accounting.  dispatched counts placements (a requeued
    # request is dispatched again), failovers counts replica deaths the
    # router drained, affinity hits/misses split fingerprinted dispatches
    # by whether the shadow matched any leading pages.  Per-replica
    # `router/replica<N>/alive|load` gauges ride alongside as extras
    # (dynamic names — deliberately outside this floor).
    "router/dispatched_total": "counter",
    "router/requeued_total": "counter",
    "router/failovers_total": "counter",
    "router/restarts_total": "counter",
    "router/retired_total": "counter",
    # graceful drains initiated (autopilot PR): scale-in, proactive
    # restart rotation and role rebalances all begin with a drain — the
    # requeue-free path, unlike failovers above
    "router/drains_total": "counter",
    "router/affinity_hits_total": "counter",
    "router/affinity_misses_total": "counter",
    # disagg (serving.fleet.disagg.DisaggRouter): KV-page migration hops
    # from prefill-role to decode-capable replicas
    "router/migrations_total": "counter",
    "router/replicas_alive": "gauge",
    "router/queue_depth": "gauge",
    "router/inflight": "gauge",
    "router/affinity_hit_rate": "gauge",
    "router/fleet_prefix_hit_rate": "gauge",
    # compile ledger (obs.compile_ledger.CompileLedger): every intercepted
    # .lower()/.compile() site counts + times here; storms are compiles
    # after warmup was declared done, thrash warnings fire when a program
    # family's distinct keys exceed its compiled-cache capacity, and the
    # cache hit/miss/eviction counters join the _CompiledLRU's own
    # eviction counter (below) so recompile churn is attributable
    "trace/compiles_total": "counter",
    "trace/compile_ms": "histogram",
    "trace/compile_storms_total": "counter",
    "trace/compile_thrash_total": "counter",
    "trace/compiled_cache_hits_total": "counter",
    "trace/compiled_cache_misses_total": "counter",
    "trace/compiled_cache_evictions_total": "counter",
    # memory ledger (obs.memory_ledger.MemoryLedger): per-subsystem device
    # bytes + peak watermarks (the gauges' sum is the logical sizing
    # model), device truth where the backend reports it, and the largest
    # compiled program's temp bytes as the workspace subsystem.  Further
    # mem/<subsystem>_bytes names are allowed as extras (this is a floor).
    "mem/params_bytes": "gauge",
    "mem/params_peak_bytes": "gauge",
    "mem/opt_state_bytes": "gauge",
    "mem/opt_state_peak_bytes": "gauge",
    "mem/kv_pool_bytes": "gauge",
    "mem/kv_pool_peak_bytes": "gauge",
    "mem/kv_cache_bytes": "gauge",
    "mem/kv_cache_peak_bytes": "gauge",
    "mem/draft_kv_bytes": "gauge",
    "mem/draft_kv_peak_bytes": "gauge",
    "mem/adapter_pool_bytes": "gauge",
    "mem/adapter_pool_peak_bytes": "gauge",
    "mem/workspace_bytes": "gauge",
    "mem/workspace_peak_bytes": "gauge",
    "mem/device_bytes_in_use": "gauge",
    "mem/device_peak_bytes": "gauge",
    "mem/device_bytes_limit": "gauge",
    "mem/live_array_bytes": "gauge",
    # fleet health monitor (obs.health.HealthMonitor): alerts currently
    # firing and total firing edges since start — the two numbers an
    # external pager scrapes alongside /healthz
    "obs/alerts_firing": "gauge",
    "obs/alerts_total": "counter",
    # fleet autopilot (serving.fleet.autopilot.Autopilot): remediation
    # actions by kind (drains counts every drain-initiating action —
    # scale-in, proactive restart, rebalance), plus the mode gauge
    # (1 = auto, 0 = page_only — the kill-switch position, scrapeable)
    "autopilot/actions_total": "counter",
    "autopilot/scale_outs_total": "counter",
    "autopilot/scale_ins_total": "counter",
    "autopilot/drains_total": "counter",
    "autopilot/restarts_total": "counter",
    "autopilot/admission_tightenings_total": "counter",
    "autopilot/rebalances_total": "counter",
    "autopilot/mode": "gauge",
    # perf attribution (obs.perf.PerfAttribution): per-family device
    # wall-time histograms on the hot path, the milli-scaled rollup gauges
    # (mfu_milli = MFU fraction x 1e3 — gauge floats, and the health
    # TrendRules watch these), and the cost-model degradation counter
    # (compile rows whose cost_analysis() omitted keys — see
    # utils.profiling.cost_report)
    "perf/prefill_device_ms": "histogram",
    "perf/prefill_chunk_device_ms": "histogram",
    "perf/decode_step_device_ms": "histogram",
    "perf/spec_round_device_ms": "histogram",
    "perf/train_step_device_ms": "histogram",
    "perf/mfu_milli": "gauge",
    "perf/mbu_milli": "gauge",
    "perf/roofline_pct_milli": "gauge",
    "perf/cost_model_missing_total": "counter",
    # live weights (weights.swapper.WeightSwapper): hot-swap attempts and
    # failures, the end-to-end swap latency (load + validate + install),
    # and the monotonic version the engine currently serves (scrapeable —
    # a mixed-version fleet mid-roll shows as diverging per-replica gauges)
    "weights/swaps_total": "counter",
    "weights/swap_failures_total": "counter",
    "weights/swap_ms": "histogram",
    "weights/weights_version": "gauge",
}


def validate_registry_metrics(registry: Any) -> None:
    """Check every :data:`REGISTRY_METRICS` name that IS registered in
    ``registry`` against its declared kind (names may be absent — a run
    without serving has no serving metrics).  Raises ``ValueError`` on a
    kind mismatch."""
    metrics = {m.name: m for m in registry.metrics()}
    for name, kind in REGISTRY_METRICS.items():
        m = metrics.get(name)
        if m is None:
            continue
        have = type(m).__name__.lower()
        if have != kind:
            raise ValueError(
                f"registry metric {name!r} is a {have}, schema declares "
                f"{kind!r} — its scalars.jsonl tags would misfile")


def validate_record(kind: str, record: dict, where: str = "") -> None:
    """Raise ValueError when ``record`` violates the ``kind`` schema."""
    schema = SCHEMAS.get(kind)
    if schema is None:
        raise ValueError(f"unknown artifact kind {kind!r} "
                         f"(known: {sorted(SCHEMAS)})")
    if not isinstance(record, dict):
        raise ValueError(f"{where or kind}: record is {type(record).__name__}, "
                         "expected object")
    for field, types in schema.items():
        if field not in record:
            raise ValueError(f"{where or kind}: missing required field "
                             f"{field!r} (present: {sorted(record)})")
        v = record[field]
        # bool is an int subclass but never a valid numeric metric value
        if isinstance(v, bool) and bool not in (
                types if isinstance(types, tuple) else (types,)):
            raise ValueError(f"{where or kind}: field {field!r} is bool, "
                             f"expected {types}")
        if not isinstance(v, types):
            raise ValueError(f"{where or kind}: field {field!r} is "
                             f"{type(v).__name__}, expected {types}")


def validate_jsonl(kind: str, path: str, max_records: int = 0) -> int:
    """Validate every line of a JSONL artifact; returns the record count.
    ``max_records`` bounds the scan (0 = all)."""
    n = 0
    with open(path) as f:
        for lineno, line in enumerate(f, 1):
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError as e:
                raise ValueError(f"{path}:{lineno}: invalid JSON ({e})")
            validate_record(kind, rec, where=f"{path}:{lineno}")
            n += 1
            if max_records and n >= max_records:
                break
    return n


def validate_flight_document(doc: dict, where: str = "flight_record") -> None:
    """Validate a flight-record document including its nested records and
    warnings."""
    validate_record("flight_record", doc, where)
    for i, rec in enumerate(doc["records"]):
        validate_record("flight_step", rec, f"{where}.records[{i}]")
    for i, w in enumerate(doc["warnings"]):
        validate_record("anomaly", w, f"{where}.warnings[{i}]")
