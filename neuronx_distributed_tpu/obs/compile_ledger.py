"""Compile ledger: every XLA compile the framework triggers, accounted.

The two resources that actually kill runs here are invisible by default:
a >24-minute cold compile looks exactly like a hang (the round-5 TPU
window died inside one), and a recompile on the serving hot path is a
silent multi-hundred-ms stall that poisons every latency percentile near
it.  :class:`CompileLedger` is the one accounting surface:

- **every ``.lower()/.compile()`` site reports here** — the AOT phase-fn
  builds and the lazily-jitted ``_CompiledLRU`` families in
  ``trace/engine.py`` (first call of a cached jit is timed and recorded,
  then the timing wrapper unwraps itself so steady-state calls pay
  nothing), the trainer-step compile in ``trainer/fit.py`` (which also
  covers the pipelined engine — its schedule compiles inside the same
  train-step jit), and ``bench.py``'s cold/warm rung timing;
- **cache events join the program events**: ``_CompiledLRU`` hit / miss /
  eviction counts land next to the compiles they explain, and evictions
  carry the evicted ``(family, key)`` so thrash is attributable;
- **recompilation pathologies are detected, not grepped for**: a family
  whose distinct keys exceed its cache capacity raises a ``thrash``
  warning (near-identical programs are cycling through the LRU — the
  ROADMAP item-1 composability smell), and ANY compile recorded after
  :meth:`declare_warmup_done` is a ``compile_storm`` — counted
  (``trace/compile_storms_total``), surfaced in the flight recorder's
  warnings, and traced as a ``compile`` span so the stall shows up in
  request waterfalls.

Rows stream to a schema-checked ``compile_ledger.jsonl``
(``obs.schemas`` kind ``compile_ledger``); ``trace/compile_ms`` /
``trace/compiles_total`` / ``trace/compiled_cache_*_total`` ride the
metric registry.  Ledger-off is allocation-free by construction: every
interception site guards on ``compile_ledger is not None`` (the
module-level :data:`LEDGER_ROWS` counter is the test hook, like
``obs.tracing.SPANS_CREATED``).
"""

from __future__ import annotations

import hashlib
import json
import os
import threading
import time
from contextlib import contextmanager
from typing import Any, Dict, Iterable, List, Optional

from neuronx_distributed_tpu.utils.logger import get_logger

logger = get_logger(__name__)

COMPILE_LEDGER_FILE = "compile_ledger.jsonl"
COMPILE_LEDGER_SCHEMA = "compile_ledger/1"

# compile wall-time histogram boundaries (ms): compiles span four orders of
# magnitude — sub-second lazy jits to the >24-minute remote-service cold
# builds the round-5 window died inside
COMPILE_MS_BUCKETS = (
    1.0, 5.0, 10.0, 50.0, 100.0, 250.0, 500.0, 1000.0, 2500.0, 5000.0,
    10000.0, 30000.0, 60000.0, 300000.0, 900000.0, 1800000.0,
)

# module-level row counter: the ledger-off overhead test reads it around a
# full serving run and asserts it never moved — zero rows are ever built
# with no ledger attached (the obs.tracing.SPANS_CREATED discipline)
LEDGER_ROWS = 0

# cost_report keys copied onto a compile row when the executable is
# available (AOT sites; lazy jits record wall time only)
_COST_KEYS = ("flops", "bytes_accessed", "argument_size_in_bytes",
              "output_size_in_bytes", "temp_size_in_bytes")


def jit_cache_size(fn: Any) -> Optional[int]:
    """Best-effort cache size of a jitted function (``fn._cache_size()``),
    jax-version-guarded: None when the attribute is missing or raises.
    Growth between polls is the fingerprint of a silent retrace/recompile
    inside jit dispatch — the one compile class the explicit interception
    sites can't see (shared by ``fit()``'s train-step poll and the serving
    engine's sampler-jit poll)."""
    size_fn = getattr(fn, "_cache_size", None)
    try:
        return int(size_fn()) if callable(size_fn) else None
    except Exception:  # pragma: no cover - jax-version-dependent
        return None


def _signature(compiled: Any) -> Optional[str]:
    """Short stable hash of the executable's sharding/donation signature —
    two compiles of the same family with different signatures are different
    programs even at equal shape keys (the near-duplicate-program smell)."""
    try:
        parts = []
        for attr in ("input_shardings", "output_shardings"):
            v = getattr(compiled, attr, None)
            if v is not None:
                parts.append(str(v))
        dn = getattr(compiled, "donated_argnums", None)
        if dn is not None:
            parts.append(str(dn))
        if not parts:
            return None
        return hashlib.blake2s("|".join(parts).encode(),
                               digest_size=8).hexdigest()
    except Exception:  # pragma: no cover - backend-dependent reprs
        return None


class CompileLedger:
    """The run's compile accounting: program rows + cache events + pathology
    detection.

    ``path`` streams every row to a ``compile_ledger.jsonl`` as it is
    recorded (append — the artifact survives a crash mid-run).
    ``registry`` receives the ``trace/compile*`` counters and the
    ``trace/compile_ms`` histogram; ``tracer`` receives a ``compile`` span
    per post-warmup compile (storms show up in request waterfalls);
    ``flight`` (a :class:`~.flight.FlightRecorder`) receives storm/thrash
    warnings next to the step anomalies; ``memory_ledger`` receives each
    AOT program's temp/output bytes (its ``workspace`` subsystem).  All
    optional, attachable late via :meth:`attach`."""

    def __init__(self, path: Optional[str] = None, registry: Any = None,
                 tracer: Any = None, flight: Any = None,
                 memory_ledger: Any = None, wall=time.time,
                 clock=time.monotonic):
        self.path = path
        self.registry = registry
        self.tracer = tracer
        self.flight = flight
        self.memory_ledger = memory_ledger
        self._wall = wall
        self._clock = clock
        self.rows: List[dict] = []
        self.warnings: List[dict] = []
        self.warmup_done = False
        self._lock = threading.Lock()
        # family -> {"keys": set, "capacity": int|None, "compiles": int,
        #            "evictions": int, "cold_ms": float, "thrashed": bool}
        self._fams: Dict[str, dict] = {}
        self.cache_hits = 0
        self.cache_misses = 0
        self.cache_evictions = 0

    # -- wiring ------------------------------------------------------------

    def attach(self, registry: Any = None, tracer: Any = None,
               flight: Any = None, memory_ledger: Any = None) -> None:
        """Fill in sinks that were not known at construction (an engine
        attaches its registry/tracer to a caller-provided ledger).  Only
        empty slots are filled — explicit construction wins."""
        if self.registry is None:
            self.registry = registry
        if self.tracer is None:
            self.tracer = tracer
        if self.flight is None:
            self.flight = flight
        if self.memory_ledger is None:
            self.memory_ledger = memory_ledger

    def set_capacity(self, family: str, capacity: int) -> None:
        """Declare a family's compiled-cache capacity — the thrash
        threshold (distinct keys beyond it are cycling the LRU)."""
        self._fam(family)["capacity"] = int(capacity)

    def _fam(self, family: str) -> dict:
        f = self._fams.get(family)
        if f is None:
            f = {"keys": set(), "capacity": None, "compiles": 0,
                 "evictions": 0, "cold_ms": 0.0, "thrashed": False,
                 "hits": 0}
            self._fams[family] = f
        return f

    def family_hits(self, family: str) -> int:
        """Steady-state cache hits recorded for one program family — the
        call-count cross-check the perf-attribution join reads (one hit ==
        one compiled execution that paid no compile)."""
        f = self._fams.get(family)
        return 0 if f is None else f["hits"]

    # -- recording ---------------------------------------------------------

    def _row(self, event: str, family: str, key: Any, kind: str,
             wall_ms: Optional[float], **extra) -> dict:
        global LEDGER_ROWS
        LEDGER_ROWS += 1
        row = {
            "schema": COMPILE_LEDGER_SCHEMA,
            "time": self._wall(),
            "mono": self._clock(),
            "event": event,
            "family": str(family),
            "key": repr(key),
            "kind": kind,
            "wall_ms": (None if wall_ms is None
                        else round(float(wall_ms), 3)),
            "after_warmup": bool(self.warmup_done),
        }
        row.update(extra)
        with self._lock:
            self.rows.append(row)
        if self.path is not None:
            try:
                parent = os.path.dirname(os.path.abspath(self.path))
                if parent:
                    os.makedirs(parent, exist_ok=True)
                with open(self.path, "a") as f:
                    f.write(json.dumps(row) + "\n")
            except OSError as e:  # telemetry IO must never kill the run
                logger.warning("compile ledger: append failed: %s", e)
        return row

    def record_compile(self, family: str, key: Any,
                       wall_ms: Optional[float], kind: str = "jit",
                       compiled: Any = None, **extra) -> dict:
        """One program compiled: ``family`` is the program family (an LRU
        name, ``context``/``decode``, ``train_step``...), ``key`` the
        shape/static key within it, ``wall_ms`` the measured compile wall
        time (None when only the event is known, e.g. a detected jit-cache
        growth), ``kind`` ``"aot"`` for ``.lower().compile()`` sites and
        ``"jit"`` for lazy first-call compiles.  ``compiled`` (the
        executable) adds cost/memory stats via
        :func:`~..utils.profiling.cost_report` and the sharding/donation
        signature hash."""
        if compiled is not None:
            from neuronx_distributed_tpu.utils.profiling import cost_report

            try:
                rep = cost_report(compiled)
            except Exception:  # pragma: no cover - backend-dependent
                rep = {}
            for k in _COST_KEYS:
                if k in rep and k not in extra:
                    extra[k] = rep[k]
            missing = rep.get("cost_keys_missing")
            if missing:
                # the cost model went blind for this program — count the
                # degradation so downstream roofline joins can tell "moves
                # no bytes" from "unreported"
                extra.setdefault("cost_keys_missing", int(missing))
                if self.registry is not None:
                    self.registry.counter(
                        "perf/cost_model_missing_total").inc(int(missing))
            sig = _signature(compiled)
            if sig is not None:
                extra.setdefault("signature", sig)
            if self.memory_ledger is not None:
                self.memory_ledger.note_program(str(family), extra)
        fam = self._fam(family)
        fam["compiles"] += 1
        fam["keys"].add(repr(key))
        if wall_ms is not None:
            fam["cold_ms"] += float(wall_ms)
        if self.warmup_done:
            extra["storm"] = True  # stamped BEFORE the row streams to disk
        row = self._row("compile", family, key, kind, wall_ms, **extra)
        reg = self.registry
        if reg is not None:
            reg.counter("trace/compiles_total").inc()
            if wall_ms is not None:
                reg.histogram("trace/compile_ms",
                              COMPILE_MS_BUCKETS).observe(float(wall_ms))
        if self.warmup_done:
            self._storm(row)
        self._check_thrash(family)
        return row

    def _storm(self, row: dict) -> None:
        """A compile after warmup was declared done: the serving latency
        pathology.  Counted, flight-warned, and traced as a ``compile``
        span covering the stall's wall-time."""
        wall = (f"{row['wall_ms']} ms"
                if row["wall_ms"] is not None
                else "an unknown wall time (detected via jit-cache growth)")
        msg = (f"compile_storm: {row['family']} key {row['key']} compiled "
               f"{wall} after warmup was declared done")
        warning = {"step": -1, "detector": "compile_storm", "message": msg,
                   "time": row["time"]}
        self.warnings.append(warning)
        logger.warning("compile ledger: %s", msg)
        if self.registry is not None:
            self.registry.counter("trace/compile_storms_total").inc()
        if self.flight is not None:
            self.flight.warnings.append(warning)
        tr = self.tracer
        if tr is not None:
            s = tr.begin("compile", family=row["family"], key=row["key"],
                         wall_ms=row["wall_ms"], storm=True)
            if row["wall_ms"]:
                # the compile just FINISHED: the span covers the stall that
                # already happened, not the instant it was noticed
                s.t_start -= row["wall_ms"] / 1e3
            tr.end(s)

    def _check_thrash(self, family: str) -> None:
        fam = self._fam(family)
        cap = fam["capacity"]
        if cap is None or fam["thrashed"] or len(fam["keys"]) <= cap:
            return
        fam["thrashed"] = True
        msg = (f"compile thrash: family {family!r} has seen "
               f"{len(fam['keys'])} distinct program keys but its compiled "
               f"cache holds {cap} — near-identical programs are cycling "
               "the LRU (every eviction is a future recompile)")
        warning = {"step": -1, "detector": "compile_thrash", "message": msg,
                   "time": self._wall()}
        self.warnings.append(warning)
        logger.warning("compile ledger: %s", msg)
        self._row("thrash", family, sorted(fam["keys"]), "event", None,
                  capacity=cap, distinct_keys=len(fam["keys"]))
        if self.registry is not None:
            self.registry.counter("trace/compile_thrash_total").inc()
        if self.flight is not None:
            self.flight.warnings.append(warning)

    @contextmanager
    def timed(self, family: str, key: Any, kind: str = "aot"):
        """Time a compile site: ``with ledger.timed("context", key) as rec:
        rec["compiled"] = lowered.compile()`` — the row is recorded on exit
        with the measured wall time (and the executable's stats when the
        body stored it under ``"compiled"``)."""
        holder: Dict[str, Any] = {}
        t0 = time.perf_counter()
        yield holder
        wall_ms = (time.perf_counter() - t0) * 1e3
        self.record_compile(family, key, wall_ms, kind=kind,
                            compiled=holder.get("compiled"))

    # -- cache events ------------------------------------------------------

    def cache_hit(self, family: str) -> None:
        self.cache_hits += 1
        self._fam(family)["hits"] += 1
        if self.registry is not None:
            self.registry.counter("trace/compiled_cache_hits_total").inc()

    def cache_miss(self, family: str) -> None:
        self.cache_misses += 1
        if self.registry is not None:
            self.registry.counter("trace/compiled_cache_misses_total").inc()

    def record_eviction(self, family: str, evicted_key: Any,
                        capacity: Optional[int] = None) -> dict:
        """An LRU dropped a compiled program — the evicted ``(family,
        key)`` is the row, so thrash is attributable to the programs
        actually cycling (the eviction log used to drop the key)."""
        self.cache_evictions += 1
        fam = self._fam(family)
        fam["evictions"] += 1
        if capacity is not None:
            fam["capacity"] = int(capacity)
        row = self._row("eviction", family, evicted_key, "event", None,
                        capacity=fam["capacity"])
        self._check_thrash(family)
        return row

    # -- warmup / storms ---------------------------------------------------

    def declare_warmup_done(self, label: str = "warmup") -> None:
        """Everything is compiled now — any compile after this is a
        ``compile_storm``.  Idempotent."""
        if self.warmup_done:
            return
        self._row("warmup_done", label, None, "event", None)
        self.warmup_done = True

    # -- queries -----------------------------------------------------------

    def compile_count(self, after_warmup_only: bool = False) -> int:
        with self._lock:
            return sum(1 for r in self.rows if r["event"] == "compile"
                       and (r["after_warmup"] or not after_warmup_only))

    @property
    def storms(self) -> int:
        return self.compile_count(after_warmup_only=True)

    def mark(self) -> int:
        """Row-count bookmark; pair with :meth:`compiles_since` to count
        the compiles inside a measurement window."""
        with self._lock:
            return len(self.rows)

    def compiles_since(self, mark: int) -> int:
        with self._lock:
            return sum(1 for r in self.rows[mark:] if r["event"] == "compile")

    def summary(self) -> dict:
        """The report-facing rollup (also what ``obs_report --compare``
        diffs between runs)."""
        with self._lock:
            rows = list(self.rows)
        return summarize_compile_records(rows, cache={
            "hits": self.cache_hits, "misses": self.cache_misses,
            "evictions": self.cache_evictions})

    def dump(self, path: Optional[str] = None) -> Optional[str]:
        """Write every row as one self-contained JSONL snapshot (streaming
        appends already keep :attr:`path` current; this is for exporting to
        a different location)."""
        path = path or self.path
        if path is None:
            return None
        parent = os.path.dirname(os.path.abspath(path))
        if parent:
            os.makedirs(parent, exist_ok=True)
        with self._lock:
            rows = list(self.rows)
        with open(path, "w") as f:
            for r in rows:
                f.write(json.dumps(r) + "\n")
        return path


def read_compile_ledger(path: str) -> List[dict]:
    """Parse a ``compile_ledger.jsonl`` (blank lines skipped)."""
    out: List[dict] = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if line:
                out.append(json.loads(line))
    return out


def summarize_compile_records(records: Iterable[dict],
                              cache: Optional[dict] = None) -> dict:
    """Rollup of ledger rows: totals, per-family breakdown, pathology
    counts — the "compile" health section of the obs report, computable
    from the artifact alone."""
    compiles = aot = 0
    cold_ms = 0.0
    cold_max = 0.0
    storms = thrash = evictions = 0
    fams: Dict[str, dict] = {}
    for r in records:
        ev = r.get("event")
        fam = fams.setdefault(r.get("family", "?"), {
            "compiles": 0, "cold_ms": 0.0, "keys": set(), "evictions": 0})
        if ev == "compile":
            compiles += 1
            fam["compiles"] += 1
            fam["keys"].add(r.get("key"))
            if r.get("kind") == "aot":
                aot += 1
            w = r.get("wall_ms")
            if w is not None:
                cold_ms += float(w)
                cold_max = max(cold_max, float(w))
                fam["cold_ms"] += float(w)
            if r.get("after_warmup"):
                storms += 1
        elif ev == "eviction":
            evictions += 1
            fam["evictions"] += 1
        elif ev == "thrash":
            thrash += 1
    out = {
        "compiles": compiles,
        "aot": aot,
        "jit": compiles - aot,
        "cold_ms_total": round(cold_ms, 3),
        "cold_ms_max": round(cold_max, 3),
        "storms": storms,
        "thrash_warnings": thrash,
        "evictions": evictions,
        "families": {
            name: {"compiles": f["compiles"],
                   "cold_ms": round(f["cold_ms"], 3),
                   "distinct_keys": len(f["keys"]),
                   "evictions": f["evictions"]}
            for name, f in sorted(fams.items()) if f["compiles"]
            or f["evictions"]},
    }
    if cache is not None:
        hits, misses = cache.get("hits", 0), cache.get("misses", 0)
        out["cache"] = {
            **cache,
            "hit_rate": (round(hits / (hits + misses), 4)
                         if hits + misses else None),
        }
    return out
