"""Unified observability subsystem (ISSUE 1 tentpole).

One telemetry layer that can answer "why was step N slow / why did the run
die / how many bytes did this program move" from persisted artifacts alone —
the reference delegates device profiling to external Neuron tools and
scatters metrics across example code (SURVEY §5.1/§5.5); our earlier port
reproduced that fragmentation across ``trainer/metrics.py``,
``trainer/scalar_log.py``, ``utils/timeline.py``, ``utils/profiling.py`` and
``tools/tpu_watch.py``.  This package correlates them:

- :mod:`.registry` — low-overhead counters / gauges / fixed-bucket
  histograms, serialized to the existing ``scalars.jsonl`` schema plus a
  Prometheus text exposition;
- :mod:`.flight` — a ring buffer of the last K step records (loss,
  grad-norm, host/device/data-wait step-time breakdown) dumped to
  ``flight_record.json`` on crash/SIGTERM, with built-in anomaly detectors
  (NaN/Inf loss, loss-spike z-score, throughput regression);
- :mod:`.hlo_audit` — compile-time collective-op counts and byte volumes
  walked out of a compiled program's HLO (the reusable form of the
  assertions in ``tests/test_hlo_collectives.py``), one audit record per
  executable;
- :mod:`.schemas` — the checked-in schema list every JSONL artifact is
  validated against (the contract downstream tooling relies on);
- :mod:`.tracing` — request-lifecycle distributed tracing for the serving
  stack (ring-bounded span tracer, ``trace_events.jsonl`` + Perfetto
  exporters) and the trainer's Chrome-trace :class:`Timeline` (moved here
  from ``utils/timeline.py``, which re-exports it);
- :mod:`.metrics_server` — stdlib HTTP ``/metrics`` (live Prometheus
  text) + ``/healthz`` endpoints over a registry (CLI:
  ``tools/metrics_server.py``; live: ``runner.py serve --metrics-port``);
- :mod:`.health` — the fleet health monitor: threshold / EWMA-trend /
  multi-window SLO burn-rate rules evaluated over live registry
  snapshots, firing/resolved edges streamed to schema-checked
  ``alerts.jsonl`` (``fit(obs=Observability(health=True))``,
  ``ServingEngine(health=...)``, ``FleetRouter(health=...)``);
- :mod:`.aggregate` — fleet-wide metric aggregation: per-replica registry
  merge (sum/max/histogram-merge per metric kind), the replica-labeled
  ``/metrics?scope=fleet`` Prometheus exposition, and the
  :class:`~.aggregate.FleetHealth` control room the router drives;
- :mod:`.report` — merges scalars + timeline traces + flight records + HLO
  audits + request traces into one run summary (CLI:
  ``tools/obs_report.py``).

:class:`Observability` glues them into the one object ``fit()`` (and any
other driver) wires in.
"""

from __future__ import annotations

import os
import time
from typing import Any, Optional

from neuronx_distributed_tpu.obs.flight import (
    AnomalyDetector,
    FlightRecorder,
    LossSpikeDetector,
    NanLossDetector,
    ThroughputRegressionDetector,
    default_detectors,
)
from neuronx_distributed_tpu.obs.compile_ledger import (
    COMPILE_LEDGER_FILE,
    CompileLedger,
    read_compile_ledger,
    summarize_compile_records,
)
from neuronx_distributed_tpu.obs.hlo_audit import (
    append_audit,
    collective_bytes,
    collective_counts,
    comm_audit,
    read_audits,
)
from neuronx_distributed_tpu.obs.memory_ledger import (
    MEMORY_BREAKDOWN_FILE,
    MemoryLedger,
    read_memory_breakdown,
)
from neuronx_distributed_tpu.obs.perf import (
    DEVICE_SPECS,
    PERF_ATTRIBUTION_FILE,
    PERF_ATTRIBUTION_SCHEMA,
    PERF_FAMILIES,
    DeviceSpec,
    PerfAttribution,
    device_spec,
    merge_perf_records,
    read_perf_attribution,
    roofline_attribution,
    summarize_perf,
)
from neuronx_distributed_tpu.obs.health import (
    ALERT_SCHEMA,
    ALERTS_FILE,
    BurnRateRule,
    HealthMonitor,
    Rule,
    ThresholdRule,
    TrendRule,
    default_rules,
    read_alerts,
)
from neuronx_distributed_tpu.obs.registry import (
    Counter,
    Gauge,
    Histogram,
    MetricRegistry,
)
from neuronx_distributed_tpu.obs.schemas import (
    REGISTRY_METRICS,
    SCHEMAS,
    validate_jsonl,
    validate_record,
    validate_registry_metrics,
)
from neuronx_distributed_tpu.obs.tracing import (
    TRACE_EVENT_SCHEMA,
    TRACE_EVENTS_FILE,
    Span,
    Tracer,
    read_trace_events,
    write_chrome_trace,
)
from neuronx_distributed_tpu.obs.transfer_audit import TransferAudit
from neuronx_distributed_tpu.utils.logger import get_logger

logger = get_logger(__name__)

# canonical artifact names inside an obs run directory — obs/report.py and
# tools/obs_report.py look these up by name
SCALARS_FILE = "scalars.jsonl"
FLIGHT_FILE = "flight_record.json"
HLO_AUDIT_FILE = "hlo_audit.jsonl"
PROMETHEUS_FILE = "metrics.prom"

# step-time-style histogram boundaries (milliseconds)
MS_BUCKETS = (1.0, 2.5, 5.0, 10.0, 25.0, 50.0, 100.0, 250.0, 500.0,
              1000.0, 2500.0, 5000.0, 10000.0, 30000.0)


class Observability:
    """The per-run telemetry hub: one registry, one flight recorder, one
    HLO-audit stream, all persisting under ``out_dir``.

    ``fit(obs=...)`` accepts either an instance (caller keeps the registry
    to add its own metrics) or a directory path (``fit`` builds one).  Every
    artifact it writes validates against :mod:`.schemas`, so downstream
    tooling (``tools/obs_report.py``, dashboards) can rely on the formats.
    """

    def __init__(
        self,
        out_dir: str,
        flight_capacity: int = 256,
        detectors: Optional[list] = None,
        timeline: Any = None,
        registry: Optional[MetricRegistry] = None,
        ledgers: bool = False,
        health: Any = False,
        perf: bool = False,
    ):
        self.out_dir = out_dir
        os.makedirs(out_dir, exist_ok=True)
        self.timeline = timeline
        self.registry = registry if registry is not None else MetricRegistry()
        self.scalars_path = os.path.join(out_dir, SCALARS_FILE)
        self.flight_path = os.path.join(out_dir, FLIGHT_FILE)
        self.hlo_audit_path = os.path.join(out_dir, HLO_AUDIT_FILE)
        self.prometheus_path = os.path.join(out_dir, PROMETHEUS_FILE)
        self.flight = FlightRecorder(
            capacity=flight_capacity,
            path=self.flight_path,
            detectors=detectors if detectors is not None else default_detectors(),
            timeline=timeline,
            registry=self.registry,
        )
        # resource ledgers (ledgers=True): compile accounting streamed to
        # compile_ledger.jsonl + per-subsystem memory watermarks with OOM
        # forensics into memory_breakdown.json — fit() threads them through
        # the train-step compile and its crash handler.  Off by default:
        # every consumer guards on `is not None`, so the hot path stays
        # allocation-free.
        self.compile_ledger: Optional[CompileLedger] = None
        self.memory_ledger: Optional[MemoryLedger] = None
        if ledgers:
            self.memory_ledger = MemoryLedger(
                registry=self.registry,
                path=os.path.join(out_dir, MEMORY_BREAKDOWN_FILE))
            self.compile_ledger = CompileLedger(
                path=os.path.join(out_dir, COMPILE_LEDGER_FILE),
                registry=self.registry, flight=self.flight,
                memory_ledger=self.memory_ledger)
        # fleet health monitor (health=True or a rule list builds one with
        # the default pack; pass a HealthMonitor to keep the rules/sink):
        # evaluated on the observe_step cadence over this hub's registry,
        # alert edges streamed to alerts.jsonl under out_dir.  Off by
        # default — every consumer guards on `is not None`, so the hot
        # path stays allocation-free (the ALERTS_EVALUATED discipline).
        # perf attribution (perf=True): per-phase device-time accounting
        # joined with compile-ledger costs into roofline/MFU records,
        # dumped to perf_attribution.jsonl on close.  Off by default —
        # consumers guard on `is not None` (the PERF_RECORDS discipline).
        self.perf: Optional[PerfAttribution] = None
        if perf:
            self.perf = PerfAttribution(
                path=os.path.join(out_dir, PERF_ATTRIBUTION_FILE),
                registry=self.registry, ledger=self.compile_ledger)
        self.health_monitor: Optional[HealthMonitor] = None
        if isinstance(health, HealthMonitor):
            self.health_monitor = health
            health.attach_registry(self.registry)
        elif health:
            if isinstance(health, str):  # a default-pack scope name
                rules = default_rules(health)
            elif isinstance(health, (list, tuple)):
                rules = list(health)
            else:
                # health=True: the hub serves BOTH fit() and serving
                # engines, so the bare boolean gets the union pack —
                # scope-specific rules over absent metrics stay silent
                rules = default_rules("all")
            self.health_monitor = HealthMonitor(
                rules, registry=self.registry,
                path=os.path.join(out_dir, ALERTS_FILE))
        self._last_step = 0
        self._closed = False
        # pre-declare the step metrics so a zero-step run still exports them
        self.registry.counter("train/steps_total")
        self.registry.histogram("train/step_time_ms", MS_BUCKETS)
        self.registry.histogram("train/data_wait_ms", MS_BUCKETS)

    # -- step path ---------------------------------------------------------

    def observe_step(self, step: int, **fields) -> list:
        """Record one training step (flight record + registry metrics);
        returns the anomaly warnings the detectors raised (possibly [])."""
        self._last_step = step
        reg = self.registry
        reg.counter("train/steps_total").inc()
        for key in ("loss", "grad_norm", "seq_per_sec"):
            if key in fields and fields[key] is not None:
                reg.gauge(f"train/{key}").set(float(fields[key]))
        if fields.get("step_time_s") is not None:
            reg.histogram("train/step_time_ms", MS_BUCKETS).observe(
                1e3 * float(fields["step_time_s"]))
        if fields.get("data_wait_s") is not None:
            reg.histogram("train/data_wait_ms", MS_BUCKETS).observe(
                1e3 * float(fields["data_wait_s"]))
        warnings = self.flight.record(step, **fields)
        if self.health_monitor is not None:
            self.health_monitor.on_step()
        return warnings

    # -- compile path ------------------------------------------------------

    def audit_executable(self, name: str, compiled: Any) -> dict:
        """Walk one compiled executable's HLO for collectives and persist
        the audit record; also mirrors the headline numbers as gauges."""
        rec = comm_audit(compiled, name=name)
        append_audit(self.hlo_audit_path, rec)
        for op, n in rec["collective_counts"].items():
            self.registry.gauge(f"hlo/{name}/{op}_count").set(float(n))
        self.registry.gauge(f"hlo/{name}/collective_bytes").set(
            float(rec["total_collective_bytes"]))
        logger.info(
            "obs: HLO audit %r: %s collectives, %.3e bytes moved",
            name, sum(rec["collective_counts"].values()),
            rec["total_collective_bytes"],
        )
        return rec

    # -- persistence -------------------------------------------------------

    def dump_scalars(self, step: Optional[int] = None) -> None:
        """Append the registry snapshot to ``scalars.jsonl`` (same schema as
        :class:`~..trainer.scalar_log.ScalarWriter`)."""
        self.registry.dump_jsonl(
            self.scalars_path, step if step is not None else self._last_step)

    def dump_flight(self, reason: str) -> Optional[str]:
        """Dump the flight-recorder ring to ``flight_record.json``."""
        return self.flight.dump(reason)

    def close(self, reason: str = "close") -> None:
        """Final persistence: last scalars snapshot, flight dump, Prometheus
        text export.  Idempotent."""
        if self._closed:
            return
        self._closed = True
        self.dump_scalars()
        self.dump_flight(reason)
        if self.memory_ledger is not None:
            try:
                self.memory_ledger.poll_device()
                self.memory_ledger.dump(reason=reason)
            except OSError as e:  # telemetry IO must never mask the exit
                logger.warning("obs: memory breakdown dump failed: %s", e)
        if self.perf is not None:
            try:
                self.perf.update_metrics()
                self.perf.dump()
            except OSError as e:  # telemetry IO must never mask the exit
                logger.warning("obs: perf attribution dump failed: %s", e)
        if self.health_monitor is not None:
            self.health_monitor.close()
        with open(self.prometheus_path, "w") as f:
            f.write(self.registry.prometheus_text())

    def __enter__(self) -> "Observability":
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        self.close("exception:%s" % exc_type.__name__ if exc_type else "close")


__all__ = [
    "Observability",
    "MetricRegistry",
    "HealthMonitor",
    "Rule",
    "ThresholdRule",
    "TrendRule",
    "BurnRateRule",
    "default_rules",
    "read_alerts",
    "ALERTS_FILE",
    "ALERT_SCHEMA",
    "CompileLedger",
    "MemoryLedger",
    "read_compile_ledger",
    "read_memory_breakdown",
    "summarize_compile_records",
    "COMPILE_LEDGER_FILE",
    "MEMORY_BREAKDOWN_FILE",
    "Counter",
    "Gauge",
    "Histogram",
    "FlightRecorder",
    "AnomalyDetector",
    "NanLossDetector",
    "LossSpikeDetector",
    "ThroughputRegressionDetector",
    "default_detectors",
    "comm_audit",
    "collective_counts",
    "collective_bytes",
    "append_audit",
    "read_audits",
    "SCHEMAS",
    "REGISTRY_METRICS",
    "validate_record",
    "validate_jsonl",
    "validate_registry_metrics",
    "TransferAudit",
    "Tracer",
    "Span",
    "read_trace_events",
    "write_chrome_trace",
    "TRACE_EVENTS_FILE",
    "TRACE_EVENT_SCHEMA",
    "PerfAttribution",
    "DeviceSpec",
    "DEVICE_SPECS",
    "device_spec",
    "roofline_attribution",
    "summarize_perf",
    "merge_perf_records",
    "read_perf_attribution",
    "PERF_ATTRIBUTION_FILE",
    "PERF_ATTRIBUTION_SCHEMA",
    "PERF_FAMILIES",
    "SCALARS_FILE",
    "FLIGHT_FILE",
    "HLO_AUDIT_FILE",
    "PROMETHEUS_FILE",
    "MS_BUCKETS",
]
