"""Live Prometheus scrape endpoint over a :class:`~.registry.MetricRegistry`.

The registry has serialized to Prometheus text since the first obs PR
(``MetricRegistry.prometheus_text``), but only as a file written at close —
nothing could scrape a RUNNING trainer or serving engine.  This module is
the missing transport: a stdlib ``http.server`` thread exposing

- ``GET /metrics``  — the Prometheus text exposition (re-rendered per
  scrape, so gauges/counters are always current); ``?scope=NAME`` selects
  an alternate renderer from ``scopes`` (the fleet wiring registers
  ``scope=fleet`` — the replica-labeled merged exposition from
  :class:`~.aggregate.FleetAggregator`);
- ``GET /healthz``  — a JSON READINESS document: the caller-supplied
  liveness probe (e.g. engine steps / active slots, or fleet replicas
  alive) merged with the attached health ``monitor``'s rule state
  (``monitor=`` — a :class:`~.health.HealthMonitor` or
  :class:`~.aggregate.FleetHealth`); a falsy ``"ok"`` — liveness gone OR
  a ``page``-severity alert firing — answers 503, so a dead-or-paging
  fleet fails load-balancer checks instead of serving stale 200s.

Attach points: ``examples/inference/runner.py serve --metrics-port N`` (a
live serving engine or fleet) and the standalone ``tools/metrics_server.py``
CLI (re-exposes a finished run's ``scalars.jsonl`` for scrape-based
backfill).  No third-party dependencies — the whole server is stdlib.
"""

from __future__ import annotations

import json
import math
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Dict, Iterable, Optional
from urllib.parse import parse_qs

from neuronx_distributed_tpu.utils.logger import get_logger

logger = get_logger(__name__)

PROM_CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"


class MetricsServer:
    """Background-thread HTTP server for ``/metrics`` + ``/healthz``.

    ``registry`` supplies the metrics text (or pass ``text_fn`` for a
    custom renderer — the CLI's scalars-file mode does).  ``scopes`` maps
    ``?scope=NAME`` to alternate renderers (unknown scopes answer 400).
    ``health_fn`` returns the liveness dict; ``monitor`` (an object with
    ``healthz()`` — a health monitor or fleet health) folds rule state
    into the same document, and the response is 503 unless BOTH agree ok.
    ``autopilot`` (an object with ``healthz_fields()`` — a fleet
    :class:`~..serving.fleet.autopilot.Autopilot`) nests its controller
    state under ``"autopilot"`` (mode, last action, actions-in-window vs
    budget) — observability only, it never flips readiness: a paused or
    budget-exhausted autopilot is an operator concern, not a reason to
    pull the fleet out of the load balancer.  ``port=0`` binds an
    ephemeral port (read :attr:`port` after construction — the test
    harness pattern)."""

    def __init__(self, registry=None, *,
                 text_fn: Optional[Callable[[], str]] = None,
                 health_fn: Optional[Callable[[], dict]] = None,
                 monitor=None,
                 autopilot=None,
                 scopes: Optional[Dict[str, Callable[[], str]]] = None,
                 port: int = 0, host: str = "0.0.0.0"):
        if registry is None and text_fn is None:
            raise ValueError("MetricsServer needs a registry or a text_fn")
        self._text_fn = (text_fn if text_fn is not None
                         else registry.prometheus_text)
        self._scopes = dict(scopes) if scopes else {}
        self._monitor = monitor
        self._autopilot = autopilot
        self._health_fn = health_fn if health_fn is not None else (
            lambda: {"ok": True})
        outer = self

        class _Handler(BaseHTTPRequestHandler):
            def do_GET(self):  # noqa: N802 (stdlib handler name)
                path, _, query = self.path.partition("?")
                if path == "/metrics":
                    params = parse_qs(query)
                    scope = params.get("scope", [None])[0]
                    if scope is None:
                        fn = outer._text_fn
                    else:
                        fn = outer._scopes.get(scope)
                        if fn is None:
                            self._reply(
                                400, "text/plain",
                                f"unknown scope {scope!r} (known: "
                                f"{sorted(outer._scopes)})\n".encode())
                            return
                    try:
                        body = fn().encode()
                    except Exception as e:  # a broken renderer is a 500
                        self._reply(500, "text/plain",
                                    f"metrics error: {e}\n".encode())
                        return
                    self._reply(200, PROM_CONTENT_TYPE, body)
                elif path == "/healthz":
                    try:
                        doc = outer._health_fn()
                        if outer._monitor is not None:
                            # readiness = liveness AND rule state: a
                            # page-severity alert takes the target out of
                            # the load balancer even while it still steps
                            hz = outer._monitor.healthz()
                            doc = {**doc, **hz,
                                   "ok": bool(doc.get("ok", True))
                                   and bool(hz.get("ok", True))}
                        if outer._autopilot is not None:
                            doc["autopilot"] = \
                                outer._autopilot.healthz_fields()
                    except Exception as e:
                        doc = {"ok": False, "error": str(e)}
                    code = 200 if doc.get("ok") else 503
                    self._reply(code, "application/json",
                                (json.dumps(doc) + "\n").encode())
                else:
                    self._reply(404, "text/plain", b"not found\n")

            def _reply(self, code, ctype, body):
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, fmt, *args):  # scrape spam off the console
                logger.debug("metrics_server: " + fmt, *args)

        self._httpd = ThreadingHTTPServer((host, port), _Handler)
        self._httpd.daemon_threads = True
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="metrics-server",
            daemon=True)
        self._thread.start()
        logger.info("metrics_server: serving /metrics and /healthz on "
                    "port %d", self.port)

    def close(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        self._thread.join(timeout=5.0)

    def __enter__(self) -> "MetricsServer":
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        self.close()


def prometheus_from_scalars(records: Iterable[dict],
                            kinds: Optional[Dict[str, str]] = None) -> str:
    """Reconstruct a Prometheus text exposition from ``scalars.jsonl``-schema
    records (latest step wins per tag) — the offline half of the scrape
    story: ``tools/metrics_server.py`` re-exposes a finished (or still
    appending) run's artifacts without the producing process.

    ``kinds`` maps metric name -> "counter"|"gauge"|"histogram" (defaults
    to :data:`~.schemas.REGISTRY_METRICS`); undeclared scalar tags render
    as gauges, and histogram-flattened ``/le_*`` + ``/count``/``/sum``
    tags are reassembled into ``_bucket``/``_count``/``_sum`` lines."""
    from neuronx_distributed_tpu.obs.registry import (
        _prom_name,
        _prom_val,
        read_histograms,
    )
    from neuronx_distributed_tpu.obs.schemas import REGISTRY_METRICS

    kinds = REGISTRY_METRICS if kinds is None else kinds
    hists = read_histograms(records if isinstance(records, list)
                            else list(records))
    records = records if isinstance(records, list) else list(records)
    latest: Dict[str, tuple] = {}
    skip_suffixes = tuple(f"{h}/{s}" for h in hists for s in ("count", "sum"))
    for r in records:
        tag = r.get("tag")
        if tag is None or "/le_" in tag or tag in skip_suffixes:
            continue
        step = int(r.get("step", 0))
        prev = latest.get(tag)
        if prev is None or step >= prev[0]:
            latest[tag] = (step, float(r["value"]))

    lines = []
    for tag in sorted(latest):
        # undeclared tags fall back on the repo-wide naming convention:
        # `*_total` is a counter, everything else a gauge
        kind = kinds.get(tag) or ("counter" if tag.endswith("_total")
                                  else "gauge")
        if kind == "histogram":
            continue  # reassembled below from the flattened tags
        pname = _prom_name(tag)
        lines.append(f"# TYPE {pname} {kind}")
        lines.append(f"{pname} {_prom_val(latest[tag][1])}")
    for name in sorted(hists):
        h = hists[name]
        pname = _prom_name(name)
        lines.append(f"# TYPE {pname} histogram")
        for le, cum in sorted(
                h["buckets"].items(),
                key=lambda kv: (math.inf if kv[0] == "inf"
                                else float(kv[0]))):
            edge = "+Inf" if le == "inf" else le
            lines.append(f'{pname}_bucket{{le="{edge}"}} {_prom_val(cum)}')
        lines.append(f"{pname}_sum {_prom_val(h['sum'])}")
        lines.append(f"{pname}_count {_prom_val(h['count'])}")
    return "\n".join(lines) + ("\n" if lines else "")
