"""Memory ledger: per-subsystem device-byte accounting + OOM forensics.

Every HBM consumer in this framework is sized blind today: the KV page
pool, the adapter pool, the draft model's contiguous caches, the params
and optimizer state, and each compiled program's workspace all carve the
same 16 GB, and the first time their sum is computed is the
RESOURCE_EXHAUSTED traceback.  :class:`MemoryLedger` is the accounting:

- **logical accounting first** (works everywhere, CPU mesh included):
  each subsystem reports its bytes (``set``/``account_tree``), exported
  live as ``mem/<subsystem>_bytes`` gauges with ``mem/<subsystem>_peak_
  bytes`` watermarks — pool sizes are the same ``page_bytes``-derived
  arithmetic the admission gates use, so the gauges' sum IS the sizing
  model;
- **device truth where the backend offers it**: :meth:`poll_device`
  reads ``device.memory_stats()`` (TPU/GPU) into ``mem/device_*`` gauges
  and falls back to a ``jax.live_arrays()`` sweep — the drift between
  the logical sum and the device number is the unaccounted residue;
- **per-program workspace** from the compile ledger's
  ``memory_analysis`` stats: the largest temp allocation across compiled
  programs is the ``workspace`` subsystem (the transient HBM a step
  needs on top of the resident pools);
- **OOM forensics**: :meth:`oom_dump` turns a RESOURCE_EXHAUSTED
  anywhere in fit/serve into a ``memory_breakdown.json`` naming the
  biggest holders — the artifact the post-mortem starts from instead of
  a dead process.

Ledger-off is allocation-free: every call site guards on
``memory_ledger is not None`` (the hot path never even builds the
argument tuples).
"""

from __future__ import annotations

import json
import os
import time
from typing import Any, Dict, List, Optional

from neuronx_distributed_tpu.utils.logger import get_logger

logger = get_logger(__name__)

MEMORY_BREAKDOWN_FILE = "memory_breakdown.json"
MEMORY_BREAKDOWN_SCHEMA = "memory_breakdown/1"

# substrings that mark an allocator exhaustion across backends (PJRT TPU,
# CPU host allocator, CUDA) — the signal that triggers the forensics dump
_OOM_MARKS = ("RESOURCE_EXHAUSTED", "RESOURCE EXHAUSTED", "Out of memory",
              "out of memory", "OOM", "Allocation failure")


def is_oom(exc: BaseException) -> bool:
    """Does this exception look like device-memory exhaustion?"""
    text = f"{type(exc).__name__}: {exc}"
    return any(m in text for m in _OOM_MARKS)


def tree_bytes(tree: Any) -> int:
    """Total array bytes across a pytree (logical: ``x.nbytes`` — for a
    sharded array this is the GLOBAL footprint; divide by shard count
    outside if per-device numbers are wanted)."""
    import jax

    total = 0
    for leaf in jax.tree.leaves(tree):
        n = getattr(leaf, "nbytes", None)
        if n is not None:
            total += int(n)
    return total


class MemoryLedger:
    """Per-subsystem byte accounting with live gauges, peak watermarks,
    device polling, and the OOM breakdown dump.

    ``registry`` (an ``obs.MetricRegistry``) receives ``mem/*`` gauges;
    ``path`` is the default ``memory_breakdown.json`` location for
    :meth:`dump` / :meth:`oom_dump`.  Both optional and attachable late.
    """

    def __init__(self, registry: Any = None, path: Optional[str] = None,
                 wall=time.time):
        self.registry = registry
        self.path = path
        self._wall = wall
        # name -> {"bytes": int, "peak_bytes": int}
        self._sub: Dict[str, dict] = {}
        # program family -> {"temp_size_in_bytes": .., "output_...": ..}
        self.programs: Dict[str, dict] = {}
        self._device: Optional[dict] = None

    # -- accounting --------------------------------------------------------

    def set(self, subsystem: str, nbytes: int) -> None:
        """Set a subsystem's current bytes; peaks are tracked and both are
        exported as gauges when a registry is attached."""
        nbytes = int(nbytes)
        s = self._sub.get(subsystem)
        if s is None:
            s = {"bytes": 0, "peak_bytes": 0}
            self._sub[subsystem] = s
        s["bytes"] = nbytes
        s["peak_bytes"] = max(s["peak_bytes"], nbytes)
        reg = self.registry
        if reg is not None:
            reg.gauge(f"mem/{subsystem}_bytes").set(float(nbytes))
            reg.gauge(f"mem/{subsystem}_peak_bytes").set(
                float(s["peak_bytes"]))

    def add(self, subsystem: str, nbytes: int) -> None:
        """Adjust a subsystem by a delta (pools that grow/shrink)."""
        cur = self._sub.get(subsystem, {"bytes": 0})["bytes"]
        self.set(subsystem, cur + int(nbytes))

    def account_tree(self, subsystem: str, tree: Any) -> int:
        """Account a pytree's array bytes as a subsystem; returns them."""
        n = tree_bytes(tree)
        self.set(subsystem, n)
        return n

    def note_program(self, family: str, info: dict) -> None:
        """Per-program temp/output bytes from the compile ledger's
        ``memory_analysis`` stats; the max temp across programs becomes
        the ``workspace`` subsystem (the transient HBM one step needs on
        top of the resident pools)."""
        keep = {k: float(v) for k, v in info.items()
                if k in ("argument_size_in_bytes", "output_size_in_bytes",
                         "temp_size_in_bytes") and v is not None}
        if not keep:
            return
        prev = self.programs.get(family, {})
        self.programs[family] = {
            k: max(keep.get(k, 0.0), prev.get(k, 0.0))
            for k in set(keep) | set(prev)}
        workspace = max((p.get("temp_size_in_bytes", 0.0)
                         for p in self.programs.values()), default=0.0)
        if workspace:
            self.set("workspace", int(workspace))

    # -- device truth ------------------------------------------------------

    def poll_device(self) -> Optional[dict]:
        """Best-effort device-memory truth: ``device.memory_stats()`` where
        the backend supports it (TPU/GPU), else a ``jax.live_arrays()``
        byte sweep, else None (pure-logical accounting).  Exports
        ``mem/device_*`` gauges and remembers the snapshot for
        :meth:`breakdown`."""
        try:
            import jax

            dev = jax.local_devices()[0]
        except Exception:
            return None
        stats = None
        try:
            stats = dev.memory_stats()
        except Exception:  # pragma: no cover - backend-dependent
            stats = None
        out: Dict[str, float] = {}
        if stats:
            for src, name in (("bytes_in_use", "device_bytes_in_use"),
                              ("peak_bytes_in_use", "device_peak_bytes"),
                              ("bytes_limit", "device_bytes_limit")):
                v = stats.get(src)
                if v is not None:
                    out[name] = float(v)
        if not out:
            try:
                out["live_array_bytes"] = float(sum(
                    getattr(x, "nbytes", 0) for x in jax.live_arrays()))
            except Exception:  # pragma: no cover
                return None
        reg = self.registry
        if reg is not None:
            for name, v in out.items():
                reg.gauge(f"mem/{name}").set(v)
        self._device = out
        return out

    def headroom_bytes(self) -> Optional[int]:
        """Device HBM headroom (limit - in use) from the last poll, when
        the backend reports both; None otherwise (callers fall back to
        their pool's logical free bytes)."""
        d = self._device
        if not d or "device_bytes_limit" not in d \
                or "device_bytes_in_use" not in d:
            return None
        return int(d["device_bytes_limit"] - d["device_bytes_in_use"])

    # -- views -------------------------------------------------------------

    @property
    def total_bytes(self) -> int:
        return sum(s["bytes"] for s in self._sub.values())

    @property
    def peak_total_bytes(self) -> int:
        return sum(s["peak_bytes"] for s in self._sub.values())

    def subsystems(self) -> Dict[str, dict]:
        return {k: dict(v) for k, v in self._sub.items()}

    def top(self, n: int = 5) -> List[list]:
        """The biggest holders, descending — what the OOM log line names."""
        ranked = sorted(self._sub.items(), key=lambda kv: -kv[1]["bytes"])
        return [[name, s["bytes"]] for name, s in ranked[:n]]

    def breakdown(self, reason: str = "snapshot") -> dict:
        """The ``memory_breakdown.json`` document (``obs.schemas`` kind
        ``memory_breakdown``)."""
        return {
            "schema": MEMORY_BREAKDOWN_SCHEMA,
            "time": self._wall(),
            "reason": reason,
            "subsystems": self.subsystems(),
            "total_bytes": self.total_bytes,
            "peak_total_bytes": self.peak_total_bytes,
            "device": self._device,
            "programs": {k: dict(v) for k, v in self.programs.items()},
            "top": self.top(),
        }

    # -- persistence -------------------------------------------------------

    def dump(self, path: Optional[str] = None,
             reason: str = "snapshot") -> Optional[str]:
        """Atomically write the breakdown document; returns the path (None
        when the ledger has no sink)."""
        path = path or self.path
        if path is None:
            return None
        doc = self.breakdown(reason)
        parent = os.path.dirname(os.path.abspath(path))
        if parent:
            os.makedirs(parent, exist_ok=True)
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(doc, f, indent=1)
        os.replace(tmp, path)
        return path

    def oom_dump(self, exc: BaseException,
                 path: Optional[str] = None) -> Optional[str]:
        """RESOURCE_EXHAUSTED forensics: when ``exc`` looks like memory
        exhaustion, poll the device one last time, dump the breakdown, and
        log the biggest holders.  Returns the dump path, or None when the
        exception is not an OOM (or the ledger has no sink)."""
        if not is_oom(exc):
            return None
        try:
            self.poll_device()
        except Exception:  # the device may be unusable mid-OOM
            pass
        holders = ", ".join(
            f"{name}={nbytes / 2**20:.1f}MiB" for name, nbytes in self.top())
        logger.error(
            "memory ledger: OOM (%s); biggest holders: %s (logical total "
            "%.1f MiB)", type(exc).__name__, holders or "none accounted",
            self.total_bytes / 2**20)
        try:
            return self.dump(path, reason=f"oom:{type(exc).__name__}")
        except OSError as e:  # forensics must never mask the real error
            logger.warning("memory ledger: OOM dump failed: %s", e)
            return None


def read_memory_breakdown(path: str) -> dict:
    with open(path) as f:
        doc = json.load(f)
    if doc.get("schema") != MEMORY_BREAKDOWN_SCHEMA:
        raise ValueError(f"{path}: schema {doc.get('schema')!r} != "
                         f"{MEMORY_BREAKDOWN_SCHEMA!r}")
    return doc
