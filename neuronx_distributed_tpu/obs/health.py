"""Fleet health monitor: a declarative rules engine over live metrics.

Three PRs of instrumentation (request traces, compile/HBM ledgers, the
``serving/*`` / ``router/*`` / ``kvcache/*`` / ``tenancy/*`` registry
metrics) produce every raw signal a production fleet needs — but nothing
in-tree *evaluates* them.  This module is the control room: a
:class:`HealthMonitor` evaluates a pack of rules on a step/scrape cadence
over live :class:`~.registry.MetricRegistry` snapshots and turns metric
movement into **alerts** with firing/resolved edges:

- :class:`ThresholdRule` — a metric (or derived value) crossing a bound:
  queue backlog, KV-headroom exhaustion, compile storms, adapter-pool
  thrash;
- :class:`TrendRule` — EWMA drift detection (a fast EWMA deviating from a
  slow one): TTFT drift, prefix-hit-rate collapse, speculative-acceptance
  collapse, throughput sag — the "it got slowly worse" class no single
  threshold catches;
- :class:`BurnRateRule` — multi-window SLO **error-budget burn rate** over
  per-class deadline attainment (the DistServe goodput framing: a request
  is *good* when it finishes within its SLO).  The SRE-workbook shape: the
  alert fires only when EVERY window's burn rate exceeds the factor — the
  short window gives reactivity, the long one statistical significance —
  so a fast pair (minutes) pages and a slow pair (hours) warns.

Edges (never steady states) are persisted: each firing→resolved transition
appends one schema-checked ``alerts.jsonl`` row (``obs.schemas`` kind
``alert``), bumps the ``obs/alerts_total`` counter and the
``obs/alerts_firing`` gauge, and — with a tracer attached — drops an
``alert`` instant so alerts land inside request waterfalls.  Hysteresis
(``fire_after`` / ``resolve_after`` consecutive evaluations) keeps
flapping metrics from spamming the stream.

Monitor-off is allocation-free: every call site in the serving/trainer hot
paths guards on ``health is not None`` (the ``SPANS_CREATED`` discipline);
the module counter :data:`ALERTS_EVALUATED` is the test hook that proves
no evaluation ever ran.
"""

from __future__ import annotations

import json
import math
import os
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from neuronx_distributed_tpu.utils.logger import get_logger

logger = get_logger(__name__)

ALERTS_FILE = "alerts.jsonl"
ALERT_SCHEMA = "alert/1"

SEVERITIES = ("info", "warn", "page")
_SEV_ORDER = {s: i for i, s in enumerate(SEVERITIES)}

# module-level evaluation counter: the monitor-off overhead test reads it
# around a full serving run and asserts it never moved — the "zero
# allocations in the hot path when off" contract, checkable without a
# profiler (the SPANS_CREATED / LEDGER_ROWS discipline)
ALERTS_EVALUATED = 0


def worst_severity(severities: Sequence[str]) -> Optional[str]:
    """The highest-ranked severity in ``severities`` (None when empty)."""
    best = None
    for s in severities:
        if best is None or _SEV_ORDER.get(s, 0) > _SEV_ORDER.get(best, 0):
            best = s
    return best


def healthz_doc(firing: Sequence[dict]) -> dict:
    """The ONE readiness contract both monitor flavors serve on
    ``/healthz``: not-ok exactly when a ``page``-severity alert is firing
    (a warned-but-serving target stays in the load balancer; a paging one
    comes out)."""
    worst = worst_severity([a["severity"] for a in firing])
    return {
        "ok": worst != "page",
        "alerts_firing": len(firing),
        "worst_severity": worst,
        "firing": [a["rule"] for a in firing],
    }


class RuleResult:
    """One rule evaluation: whether the condition holds right now, plus the
    evidence (observed value vs bound) the alert row carries."""

    __slots__ = ("firing", "observed", "bound", "window", "attrs")

    def __init__(self, firing: bool, observed: Optional[float] = None,
                 bound: Optional[float] = None, window: Optional[str] = None,
                 attrs: Optional[dict] = None):
        self.firing = bool(firing)
        self.observed = observed
        self.bound = bound
        self.window = window
        self.attrs = attrs or {}


class EvalContext:
    """What a rule sees at evaluation time: the metrics snapshot, the
    monotonic instant, and the monitor's per-class SLO event windows."""

    __slots__ = ("snapshot", "now", "monitor")

    def __init__(self, snapshot: dict, now: float,
                 monitor: "Optional[HealthMonitor]" = None):
        self.snapshot = snapshot
        self.now = now
        self.monitor = monitor

    def value(self, name: str) -> Optional[float]:
        """A counter/gauge value from the snapshot (None when absent or a
        histogram lives under the name)."""
        v = self.snapshot.get(name)
        if v is None or isinstance(v, dict):
            return None
        return float(v)

    def hist(self, name: str) -> Optional[dict]:
        """A histogram summary (``{"count", "sum", "buckets"}``) or None."""
        v = self.snapshot.get(name)
        return v if isinstance(v, dict) else None

    def window_counts(self, priority: str, window_s: float
                      ) -> Tuple[int, int]:
        """``(good, bad)`` SLO events of ``priority`` inside the trailing
        ``window_s`` seconds (zeros without a monitor — burn rules need
        the event stream)."""
        if self.monitor is None:
            return 0, 0
        return self.monitor._window_counts(priority, window_s, self.now)


class Rule:
    """Base rule: a name, a severity, and firing hysteresis.

    ``fire_after`` / ``resolve_after`` are CONSECUTIVE evaluations the
    condition must hold / clear before the state transitions — a flapping
    metric produces one firing edge, not one per oscillation.  Subclasses
    implement :meth:`evaluate` returning a :class:`RuleResult`, or None
    for "no observation this round" (state held, streaks reset)."""

    def __init__(self, name: str, severity: str = "warn", *,
                 fire_after: int = 1, resolve_after: int = 1):
        if severity not in SEVERITIES:
            raise ValueError(f"rule {name!r}: severity must be one of "
                             f"{SEVERITIES}, got {severity!r}")
        if fire_after < 1 or resolve_after < 1:
            raise ValueError(f"rule {name!r}: fire_after/resolve_after must "
                             "be >= 1")
        self.name = name
        self.severity = severity
        self.fire_after = int(fire_after)
        self.resolve_after = int(resolve_after)

    def evaluate(self, ctx: EvalContext) -> Optional[RuleResult]:
        raise NotImplementedError


_OPS: Dict[str, Callable[[float, float], bool]] = {
    ">": lambda v, b: v > b,
    ">=": lambda v, b: v >= b,
    "<": lambda v, b: v < b,
    "<=": lambda v, b: v <= b,
}


class ThresholdRule(Rule):
    """Fire when a value crosses a bound.

    The value is ``metric``'s snapshot value, or ``value_fn(ctx)`` when
    given (None = no observation).  ``rate=True`` observes the DELTA of
    the metric between evaluations instead of its level — the right shape
    for monotone counters (compile storms, adapter evictions): the alert
    fires while the counter is MOVING and resolves when it goes quiet."""

    def __init__(self, name: str, metric: Optional[str] = None,
                 bound: float = 0.0, *, op: str = ">",
                 value_fn: Optional[Callable[[EvalContext],
                                             Optional[float]]] = None,
                 rate: bool = False, severity: str = "warn",
                 fire_after: int = 1, resolve_after: int = 1):
        super().__init__(name, severity, fire_after=fire_after,
                         resolve_after=resolve_after)
        if metric is None and value_fn is None:
            raise ValueError(f"rule {name!r}: needs metric= or value_fn=")
        if op not in _OPS:
            raise ValueError(f"rule {name!r}: op must be one of "
                             f"{sorted(_OPS)}, got {op!r}")
        self.metric = metric
        self.bound = float(bound)
        self.op = op
        self.value_fn = value_fn
        self.rate = rate
        self._prev: Optional[float] = None

    def evaluate(self, ctx: EvalContext) -> Optional[RuleResult]:
        v = (self.value_fn(ctx) if self.value_fn is not None
             else ctx.value(self.metric))
        if v is None:
            return None
        if self.rate:
            prev, self._prev = self._prev, v
            if prev is None:
                return None  # first sight: no delta yet
            v = v - prev
        return RuleResult(_OPS[self.op](v, self.bound), observed=v,
                          bound=self.bound)


class TrendRule(Rule):
    """EWMA drift: a fast EWMA deviating from a slow one by more than
    ``ratio`` in the bad ``direction``.

    ``direction="up"`` fires when ``fast > ratio * slow`` (a latency that
    drifted up); ``direction="down"`` fires when ``fast < slow / ratio``
    (a hit rate / acceptance rate / throughput that collapsed).  The first
    ``warmup`` samples only feed the EWMAs (no verdict while the baseline
    forms), and ``min_slow`` suppresses verdicts while the slow baseline
    sits below a floor (a 0-lookup hit rate is not a collapse).

    The value is ``metric``'s level, or ``value_fn(ctx)`` — the default
    rule pack derives windowed rates (counter deltas per evaluation) and
    histogram window-means through closures over :class:`_Delta` /
    :class:`_Rate` / :class:`_HistWindowMean`."""

    def __init__(self, name: str, metric: Optional[str] = None, *,
                 value_fn: Optional[Callable[[EvalContext],
                                             Optional[float]]] = None,
                 direction: str = "up", ratio: float = 2.0,
                 fast_alpha: float = 0.5, slow_alpha: float = 0.1,
                 warmup: int = 5, min_slow: Optional[float] = None,
                 severity: str = "warn", fire_after: int = 1,
                 resolve_after: int = 1):
        super().__init__(name, severity, fire_after=fire_after,
                         resolve_after=resolve_after)
        if metric is None and value_fn is None:
            raise ValueError(f"rule {name!r}: needs metric= or value_fn=")
        if direction not in ("up", "down"):
            raise ValueError(f"rule {name!r}: direction must be 'up' or "
                             f"'down', got {direction!r}")
        if ratio <= 1.0:
            raise ValueError(f"rule {name!r}: ratio must be > 1, "
                             f"got {ratio}")
        self.metric = metric
        self.value_fn = value_fn
        self.direction = direction
        self.ratio = float(ratio)
        self.fast_alpha = float(fast_alpha)
        self.slow_alpha = float(slow_alpha)
        self.warmup = int(warmup)
        self.min_slow = min_slow
        self.fast: Optional[float] = None
        self.slow: Optional[float] = None
        self._samples = 0

    def evaluate(self, ctx: EvalContext) -> Optional[RuleResult]:
        v = (self.value_fn(ctx) if self.value_fn is not None
             else ctx.value(self.metric))
        if v is None or not math.isfinite(v):
            return None
        if self.fast is None:
            self.fast = self.slow = v
        else:
            self.fast += self.fast_alpha * (v - self.fast)
            self.slow += self.slow_alpha * (v - self.slow)
        self._samples += 1
        if self._samples <= self.warmup:
            return None
        if self.min_slow is not None and abs(self.slow) < self.min_slow:
            return None
        if self.direction == "up":
            bound = self.ratio * self.slow
            firing = self.fast > bound
        else:
            bound = self.slow / self.ratio
            firing = self.fast < bound
        return RuleResult(firing, observed=self.fast, bound=bound,
                          attrs={"slow_ewma": self.slow})


class BurnRateRule(Rule):
    """Multi-window SLO error-budget burn rate over per-class deadline
    attainment.

    ``objective`` is the SLO target (0.99 = 99% of requests good); the
    error budget is ``1 - objective``.  Over each trailing window, the
    burn rate is ``error_fraction / budget`` — burn 1.0 spends the budget
    exactly at the SLO period's pace, burn ``N`` exhausts it ``N``× too
    fast.  The rule fires only when EVERY window in ``windows`` burns at
    ``>= factor`` (short window = reactivity, long window = significance —
    the multiwindow AND from the SRE workbook), and won't fire on fewer
    than ``min_events`` events in the SHORTEST window (resolving is always
    allowed; an empty window burns 0).  Events arrive through
    :meth:`HealthMonitor.note_request` — the engine feeds one per terminal
    request (good = finished within its deadline)."""

    def __init__(self, name: str, *, priority: str = "interactive",
                 objective: float = 0.99,
                 windows: Sequence[float] = (300.0, 3600.0),
                 factor: float = 14.4, min_events: int = 4,
                 severity: str = "page", fire_after: int = 1,
                 resolve_after: int = 1):
        super().__init__(name, severity, fire_after=fire_after,
                         resolve_after=resolve_after)
        if not 0.0 < objective < 1.0:
            raise ValueError(f"rule {name!r}: objective must be in (0, 1), "
                             f"got {objective}")
        if not windows or any(w <= 0 for w in windows):
            raise ValueError(f"rule {name!r}: windows must be positive, "
                             f"got {windows}")
        self.priority = priority
        self.objective = float(objective)
        self.budget = 1.0 - float(objective)
        self.windows = tuple(sorted(float(w) for w in windows))
        self.factor = float(factor)
        self.min_events = int(min_events)

    def burn_rates(self, ctx: EvalContext) -> List[Tuple[float, float, int]]:
        """``[(window_s, burn, events), ...]`` — exposed for tests so the
        hand-computed fixtures check the same arithmetic the alert uses."""
        out = []
        for w in self.windows:
            good, bad = ctx.window_counts(self.priority, w)
            total = good + bad
            err = (bad / total) if total else 0.0
            out.append((w, err / self.budget, total))
        return out

    def evaluate(self, ctx: EvalContext) -> Optional[RuleResult]:
        rates = self.burn_rates(ctx)
        firing = all(burn >= self.factor for _, burn, _ in rates)
        if firing and rates[0][2] < self.min_events:
            firing = False  # too little evidence in the shortest window
        label = "+".join(f"{int(w)}s" for w, _, _ in rates)
        # the limiting (smallest) burn is the honest observed value: the
        # alert fires exactly when IT clears the factor
        observed = min(burn for _, burn, _ in rates)
        return RuleResult(firing, observed=observed, bound=self.factor,
                          window=label,
                          attrs={"objective": self.objective,
                                 "events": rates[0][2]})


# -- derived-value helpers for the default pack ------------------------------

class _Delta:
    """Delta of a counter between evaluations (None at first sight)."""

    def __init__(self, metric: str):
        self.metric = metric
        self._prev: Optional[float] = None

    def __call__(self, ctx: EvalContext) -> Optional[float]:
        v = ctx.value(self.metric)
        if v is None:
            return None
        prev, self._prev = self._prev, v
        return None if prev is None else v - prev


class _Rate:
    """Per-second rate of a counter between evaluations."""

    def __init__(self, metric: str):
        self.metric = metric
        self._prev: Optional[Tuple[float, float]] = None

    def __call__(self, ctx: EvalContext) -> Optional[float]:
        v = ctx.value(self.metric)
        if v is None:
            return None
        prev, self._prev = self._prev, (v, ctx.now)
        if prev is None or ctx.now <= prev[1]:
            return None
        return (v - prev[0]) / (ctx.now - prev[1])


class _WindowRatio:
    """Windowed success ratio from two counters' deltas between
    evaluations: ``d(num) / (d(num) + d(den))`` — e.g. prefix hits over
    hits+misses, or accepted over proposed.  None when nothing moved."""

    def __init__(self, num: str, den: str):
        self.num = num
        self.den = den
        self._prev: Optional[Tuple[float, float]] = None

    def __call__(self, ctx: EvalContext) -> Optional[float]:
        n, d = ctx.value(self.num), ctx.value(self.den)
        if n is None or d is None:
            return None
        prev, self._prev = self._prev, (n, d)
        if prev is None:
            return None
        dn, dd = n - prev[0], d - prev[1]
        total = dn + dd
        return None if total <= 0 else dn / total


class _WindowFraction:
    """Windowed fraction from two counters' deltas between evaluations:
    ``d(num) / d(den)`` where num is a SUBSET of den — e.g. accepted out
    of proposed draft tokens.  None when the denominator did not move."""

    def __init__(self, num: str, den: str):
        self.num = num
        self.den = den
        self._prev: Optional[Tuple[float, float]] = None

    def __call__(self, ctx: EvalContext) -> Optional[float]:
        n, d = ctx.value(self.num), ctx.value(self.den)
        if n is None or d is None:
            return None
        prev, self._prev = self._prev, (n, d)
        if prev is None:
            return None
        dd = d - prev[1]
        return None if dd <= 0 else (n - prev[0]) / dd


class _HistWindowMean:
    """Mean of a histogram's NEW observations since the last evaluation
    (None when no new samples landed) — the windowed TTFT/latency feed the
    drift rules trend on."""

    def __init__(self, metric: str):
        self.metric = metric
        self._prev: Optional[Tuple[float, float]] = None

    def __call__(self, ctx: EvalContext) -> Optional[float]:
        h = ctx.hist(self.metric)
        if h is None:
            return None
        count, total = float(h.get("count", 0)), float(h.get("sum", 0.0))
        prev, self._prev = self._prev, (count, total)
        if prev is None:
            return None
        dc = count - prev[0]
        return None if dc <= 0 else (total - prev[1]) / dc


def _kv_headroom_frac(ctx: EvalContext) -> Optional[float]:
    total = ctx.value("kvcache/pages_total")
    if not total:
        return None
    in_use = ctx.value("kvcache/pages_in_use") or 0.0
    return max(1.0 - in_use / total, 0.0)


def default_rules(scope: str = "serving", *,
                  slo_objective: float = 0.99,
                  fast_windows: Sequence[float] = (300.0, 3600.0),
                  slow_windows: Sequence[float] = (3600.0, 21600.0),
                  fast_factor: float = 14.4, slow_factor: float = 6.0,
                  classes: Sequence[str] = ("interactive", "batch"),
                  queue_depth_bound: float = 64.0,
                  kv_headroom_frac: float = 0.05,
                  adapter_evictions_per_eval: float = 8.0) -> List[Rule]:
    """The default rule pack per scope.

    - ``serving``: one engine — backlog / headroom thresholds, the four
      EWMA drift rules, compile-storm and adapter-thrash rate rules, and
      the per-class fast (page) + slow (warn) burn-rate pairs;
    - ``fleet``: evaluated over the MERGED fleet snapshot — router
      backlog, failover rate, pool-wide KV headroom, fleet-level drift
      and burn rules (``replica_down`` itself is an externally-driven
      condition the router raises, not a metric rule);
    - ``train``: a trainer — throughput sag and compile storms (loss
      anomalies stay with the flight recorder's detectors);
    - ``all``: the union pack for an ``Observability(health=True)`` hub
      that may back either a trainer or a serving engine — the serving
      pack plus the train-scope rules under distinct names (rules over
      absent metrics stay silent).

    Every scope additionally carries the perf-attribution drift rules
    (``mfu_sag`` over ``perf/mfu_milli``, ``roofline_drift`` over
    ``perf/roofline_pct_milli``) — silent unless the run profiles with
    ``Observability(perf=True)``.
    """
    if scope not in ("serving", "fleet", "train", "all"):
        raise ValueError(f"unknown rule scope {scope!r}")
    rules: List[Rule] = [
        ThresholdRule("compile_storm", "trace/compile_storms_total",
                      0.0, op=">", rate=True, severity="warn"),
        # perf-attribution drift (every scope: the perf/* gauges exist for
        # trainers and engines alike, and rules over absent metrics stay
        # silent).  Milli-unit gauges; min_slow keeps a sub-0.1%-MFU
        # baseline — calibration noise, not utilization — from "sagging".
        TrendRule("mfu_sag", "perf/mfu_milli",
                  direction="down", ratio=1.5, warmup=8, min_slow=1.0,
                  severity="warn", fire_after=2, resolve_after=2),
        TrendRule("roofline_drift", "perf/roofline_pct_milli",
                  direction="down", ratio=1.5, warmup=8, min_slow=1.0,
                  severity="warn", fire_after=2, resolve_after=2),
    ]
    train_sag = TrendRule(
        "train_throughput_sag" if scope == "all" else "throughput_sag",
        "train/seq_per_sec", direction="down", ratio=1.5, warmup=8,
        min_slow=1e-9, severity="warn", fire_after=2, resolve_after=2)
    if scope == "train":
        rules.append(train_sag)
        return rules
    if scope == "all":
        rules.append(train_sag)
        scope = "serving"
    if scope == "fleet":
        rules += [
            ThresholdRule("router_backlog", "router/queue_depth",
                          queue_depth_bound, op=">=", severity="warn",
                          fire_after=2, resolve_after=2),
            ThresholdRule("failover_storm", "router/failovers_total",
                          0.0, op=">", rate=True, severity="warn"),
        ]
    else:
        rules += [
            ThresholdRule("queue_backlog", "serving/queue_depth",
                          queue_depth_bound, op=">=", severity="warn",
                          fire_after=2, resolve_after=2),
            ThresholdRule("adapter_thrash", "tenancy/adapter_evictions_total",
                          adapter_evictions_per_eval, op=">", rate=True,
                          severity="warn"),
        ]
    rules += [
        ThresholdRule("kv_headroom", value_fn=_kv_headroom_frac,
                      bound=kv_headroom_frac, op="<", severity="warn",
                      fire_after=2, resolve_after=2),
        TrendRule("ttft_drift", value_fn=_HistWindowMean("serving/ttft_ms"),
                  direction="up", ratio=2.0, warmup=5, min_slow=1e-6,
                  severity="warn", fire_after=2, resolve_after=2),
        TrendRule("prefix_hit_collapse",
                  value_fn=_WindowRatio("kvcache/prefix_hits_total",
                                        "kvcache/prefix_misses_total"),
                  direction="down", ratio=2.0, warmup=5, min_slow=0.05,
                  severity="warn", fire_after=2, resolve_after=2),
        # accepted is a SUBSET of proposed, so this is a fraction of the
        # proposed delta — not a _WindowRatio over two disjoint counters
        TrendRule("spec_acceptance_collapse",
                  value_fn=_WindowFraction("serving/spec_accepted_total",
                                           "serving/spec_proposed_total"),
                  direction="down", ratio=1.5, warmup=5, min_slow=0.05,
                  severity="warn", fire_after=2, resolve_after=2),
        TrendRule("throughput_sag",
                  value_fn=_Rate("serving/tokens_total"
                                 if scope == "serving"
                                 else "router/dispatched_total"),
                  direction="down", ratio=2.0, warmup=8, min_slow=1e-9,
                  severity="warn", fire_after=3, resolve_after=2),
    ]
    for cls in classes:
        rules.append(BurnRateRule(
            f"slo_burn_fast_{cls}", priority=cls, objective=slo_objective,
            windows=fast_windows, factor=fast_factor, severity="page"))
        rules.append(BurnRateRule(
            f"slo_burn_slow_{cls}", priority=cls, objective=slo_objective,
            windows=slow_windows, factor=slow_factor, severity="warn",
            fire_after=2, resolve_after=2))
    return rules


# -- alert persistence -------------------------------------------------------

class AlertSink:
    """Append-only ``alerts.jsonl`` writer, shareable across monitors (a
    fleet's per-replica monitors and its fleet monitor stream to ONE
    file).  The file is created eagerly so a quiet run still leaves the
    artifact; every record is validated against the checked-in ``alert``
    schema before it is written."""

    def __init__(self, path: str):
        self.path = path
        parent = os.path.dirname(os.path.abspath(path))
        if parent:
            os.makedirs(parent, exist_ok=True)
        self._f = open(path, "a")

    def write(self, record: dict) -> None:
        from neuronx_distributed_tpu.obs.schemas import validate_record

        validate_record("alert", record)  # the emitter honors its schema
        self._f.write(json.dumps(record) + "\n")
        self._f.flush()

    def close(self) -> None:
        if self._f is not None:
            self._f.close()
            self._f = None


def read_alerts(path: str) -> List[dict]:
    """Parse an ``alerts.jsonl`` file (blank lines skipped)."""
    out: List[dict] = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if line:
                out.append(json.loads(line))
    return out


class _Active:
    """Per-(rule, key) live state: current firing flag, transition streak,
    and the firing-edge instant (for resolve-row durations)."""

    __slots__ = ("firing", "streak", "since", "severity", "window",
                 "observed", "bound")

    def __init__(self):
        self.firing = False
        self.streak = 0
        self.since: Optional[float] = None
        self.severity = "warn"
        self.window: Optional[str] = None
        self.observed: Optional[float] = None
        self.bound: Optional[float] = None


class HealthMonitor:
    """Evaluate ``rules`` over registry snapshots; stream alert edges.

    ``registry`` supplies the default snapshot (and receives the
    ``obs/alerts_*`` metrics); ``path`` opens an own :class:`AlertSink`,
    ``sink`` shares an existing one (a fleet's monitors share the file).
    ``clock`` must be the SAME clock as the system under watch (the
    engine/router's injectable clock) so alert edges share the spans' and
    stats' timescale; ``wall`` stamps the shared-epoch ``time`` field.
    ``eval_every`` thins the per-step cadence (:meth:`on_step` evaluates
    every N-th call); ``replica`` tags every row this monitor writes.

    External conditions (:meth:`set_condition`) ride the same edge
    machinery without a metric rule — the fleet router raises
    ``replica_down`` on failover and clears it on warm restart."""

    def __init__(self, rules: Optional[Sequence[Rule]] = None, *,
                 registry: Any = None, path: Optional[str] = None,
                 sink: Optional[AlertSink] = None,
                 clock: Callable[[], float] = time.monotonic,
                 wall: Callable[[], float] = time.time,
                 tracer: Any = None, replica: int = -1,
                 eval_every: int = 1, max_edges: int = 4096):
        if path is not None and sink is not None:
            raise ValueError("pass path= or sink=, not both")
        if eval_every < 1:
            raise ValueError(f"eval_every must be >= 1, got {eval_every}")
        self.rules = list(rules) if rules is not None else default_rules()
        names = [r.name for r in self.rules]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate rule names: {sorted(names)}")
        self.sink = sink if sink is not None else (
            AlertSink(path) if path is not None else None)
        self._own_sink = sink is None and path is not None
        self.tracer = tracer
        self.replica = int(replica)
        self.eval_every = int(eval_every)
        self._clock = clock
        self._wall = wall
        self._tick = 0
        self.evaluations = 0
        self._active: Dict[Tuple[str, str], _Active] = {}
        # bounded edge history: benches/tests read firing evidence without
        # re-parsing the jsonl (oldest dropped first)
        self.edges: deque = deque(maxlen=max_edges)
        # per-class SLO event windows feeding the burn-rate rules
        self._events: Dict[str, deque] = {}
        self._retention_s = max(
            [w for r in self.rules if isinstance(r, BurnRateRule)
             for w in r.windows] or [3600.0])
        self.registry = None
        self.attach_registry(registry)

    def attach_registry(self, registry: Any) -> None:
        """Late-bind the monitor's registry (the engine/router attach
        path): the rules' default snapshot source plus the home of the
        ``obs/alerts_*`` pair, pre-declared so a quiet run still exports
        them.  No-op when a registry is already bound or None is given."""
        if self.registry is not None or registry is None:
            return
        self.registry = registry
        registry.gauge("obs/alerts_firing")
        registry.counter("obs/alerts_total")

    # -- event feed (burn-rate rules) --------------------------------------

    def note_request(self, good: bool, priority: str = "interactive",
                     now: Optional[float] = None) -> None:
        """One terminal request's SLO outcome (good = finished within its
        deadline) — the burn-rate rules' event stream."""
        now = self._clock() if now is None else now
        q = self._events.get(priority)
        if q is None:
            q = self._events[priority] = deque()
        q.append((now, bool(good)))
        self._prune(q, now)

    def note_output(self, out: Any, now: Optional[float] = None) -> None:
        """Derive the SLO outcome from a terminal ``RequestOutput``: good =
        FINISHED within its deadline (deadline-less requests are good when
        they finish — shed/failed/timed-out requests burn budget)."""
        good = (out.state == "finished"
                and (out.deadline_s is None
                     or out.total_ms <= out.deadline_s * 1e3))
        self.note_request(good, getattr(out, "priority", "interactive"), now)

    def _prune(self, q: deque, now: float) -> None:
        horizon = now - self._retention_s
        while q and q[0][0] < horizon:
            q.popleft()

    def _window_counts(self, priority: str, window_s: float,
                       now: float) -> Tuple[int, int]:
        q = self._events.get(priority)
        if not q:
            return 0, 0
        horizon = now - window_s
        good = bad = 0
        for t, ok in reversed(q):
            if t < horizon:
                break
            if ok:
                good += 1
            else:
                bad += 1
        return good, bad

    # -- evaluation --------------------------------------------------------

    def on_step(self, now: Optional[float] = None) -> List[dict]:
        """Per-step cadence hook: evaluates every ``eval_every``-th call
        (returns the edges emitted, [] on skipped ticks)."""
        self._tick += 1
        if self._tick % self.eval_every:
            return []
        return self.evaluate(now)

    def evaluate(self, now: Optional[float] = None,
                 snapshot: Optional[dict] = None) -> List[dict]:
        """Evaluate every rule once; returns the alert edges emitted."""
        global ALERTS_EVALUATED
        ALERTS_EVALUATED += 1
        self.evaluations += 1
        now = self._clock() if now is None else now
        if snapshot is None:
            snapshot = self.registry.snapshot() \
                if self.registry is not None else {}
        for q in self._events.values():
            self._prune(q, now)
        ctx = EvalContext(snapshot, now, self)
        emitted: List[dict] = []
        for rule in self.rules:
            res = rule.evaluate(ctx)
            st = self._active.setdefault((rule.name, ""), _Active())
            if res is None:
                st.streak = 0  # no observation: hold state, reset streaks
                continue
            st.observed, st.bound = res.observed, res.bound
            st.window = res.window
            st.severity = rule.severity
            if res.firing == st.firing:
                st.streak = 0
                continue
            st.streak += 1
            need = rule.fire_after if res.firing else rule.resolve_after
            if st.streak < need:
                continue
            edge = self._transition(rule.name, "", st, res.firing, now,
                                    severity=rule.severity,
                                    window=res.window,
                                    observed=res.observed, bound=res.bound,
                                    attrs=res.attrs)
            emitted.append(edge)
        self._export_gauges()
        return emitted

    def set_condition(self, rule: str, firing: bool, *, key: str = "",
                      severity: str = "page",
                      observed: Optional[float] = None,
                      bound: Optional[float] = None,
                      window: Optional[str] = None,
                      now: Optional[float] = None, **attrs) -> Optional[dict]:
        """Externally-driven alert (no metric rule): idempotent edge set/
        clear keyed by ``(rule, key)`` — e.g. ``replica_down`` keyed by
        replica id.  Returns the emitted edge record, or None when the
        state did not change."""
        if severity not in SEVERITIES:
            raise ValueError(f"severity must be one of {SEVERITIES}, "
                             f"got {severity!r}")
        now = self._clock() if now is None else now
        st = self._active.setdefault((rule, key), _Active())
        if st.firing == bool(firing):
            return None
        st.severity = severity
        st.observed, st.bound, st.window = observed, bound, window
        if key:
            attrs = {"key": key, **attrs}
        edge = self._transition(rule, key, st, bool(firing), now,
                                severity=severity, window=window,
                                observed=observed, bound=bound, attrs=attrs)
        self._export_gauges()
        return edge

    def _transition(self, rule: str, key: str, st: _Active, firing: bool,
                    now: float, *, severity: str, window: Optional[str],
                    observed: Optional[float], bound: Optional[float],
                    attrs: dict) -> dict:
        st.firing = firing
        st.streak = 0
        record = {
            "schema": ALERT_SCHEMA,
            "time": self._wall(),
            "mono": now,
            "rule": rule,
            "severity": severity,
            "state": "firing" if firing else "resolved",
            "window": window,
            "observed": (float(observed) if observed is not None
                         and math.isfinite(observed) else None),
            "bound": (float(bound) if bound is not None
                      and math.isfinite(bound) else None),
            "replica": self.replica,
            **attrs,
        }
        if firing:
            st.since = now
            if self.registry is not None:
                self.registry.counter("obs/alerts_total").inc()
        elif st.since is not None:
            record["duration_s"] = round(max(now - st.since, 0.0), 6)
            st.since = None
        self.edges.append(record)
        if self.sink is not None:
            self.sink.write(record)
        if self.tracer is not None:
            # alerts land in request waterfalls: a batch-level instant on
            # the same monotonic timescale as the engine's spans
            self.tracer.instant("alert", t=now, rule=rule,
                                severity=severity, state=record["state"],
                                observed=record["observed"],
                                bound=record["bound"])
        log = (logger.warning if severity == "page" or firing
               else logger.info)
        log("health: alert %r %s (severity %s, observed %s vs bound %s%s)",
            rule, record["state"], severity, record["observed"],
            record["bound"], f", window {window}" if window else "")
        return record

    def _export_gauges(self) -> None:
        if self.registry is not None:
            self.registry.gauge("obs/alerts_firing").set(
                float(sum(1 for st in self._active.values() if st.firing)))

    # -- views -------------------------------------------------------------

    def firing(self) -> List[dict]:
        """Currently-firing alerts, worst first."""
        out = []
        for (rule, key), st in self._active.items():
            if not st.firing:
                continue
            out.append({"rule": rule, "key": key, "severity": st.severity,
                        "window": st.window, "observed": st.observed,
                        "bound": st.bound, "since": st.since})
        out.sort(key=lambda a: -_SEV_ORDER.get(a["severity"], 0))
        return out

    def worst_severity(self) -> Optional[str]:
        return worst_severity([a["severity"] for a in self.firing()])

    def healthz(self) -> dict:
        """Readiness document for ``/healthz`` (:func:`healthz_doc`)."""
        return healthz_doc(self.firing())

    def page_edges(self) -> int:
        """Firing edges at ``page`` severity seen so far (bench gating)."""
        return sum(1 for e in self.edges
                   if e["state"] == "firing" and e["severity"] == "page")

    def close(self) -> None:
        if self.sink is not None and self._own_sink:
            self.sink.close()
