"""Compile-time HLO communication audit.

Walks a compiled program's HLO text for collective ops (all-reduce /
all-gather / reduce-scatter / collective-permute / all-to-all), counting
them and summing their output byte volumes — the reusable library form of
the assertions in ``tests/test_hlo_collectives.py``, which pin collective
budgets for the TP+SP train step.  The reference has no compile-time
collective accounting at all (its perf regressions surface only on Trn1
metrics dashboards); here every compiled executable can leave one audit
record behind, so "how many bytes did this program move" is answerable from
artifacts alone.

Byte volumes are computed from each collective's RESULT shape(s) — for
all-reduce that equals the tensor size being reduced, for all-gather the
gathered output, for reduce-scatter the scattered shard.  It is a
per-execution lower bound on interconnect traffic (actual wire bytes depend
on the algorithm, e.g. ring vs tree), which is exactly what a regression
diff needs: the quantity is stable across XLA versions while absolute wire
bytes are not.
"""

from __future__ import annotations

import json
import math
import re
import time
from typing import Any, Dict, List

from neuronx_distributed_tpu.utils.profiling import cost_report

HLO_AUDIT_SCHEMA = "hlo_audit_v1"

COLLECTIVE_OPS = (
    "all-reduce",
    "all-gather",
    "reduce-scatter",
    "collective-permute",
    "all-to-all",
)

# HLO primitive-type byte widths (PrimitiveType names as printed in HLO text)
_DTYPE_BYTES = {
    "pred": 1, "s4": 1, "u4": 1, "s8": 1, "u8": 1,
    "f8e4m3": 1, "f8e5m2": 1, "f8e4m3fn": 1, "f8e4m3b11fnuz": 1,
    "f8e5m2fnuz": 1, "f8e4m3fnuz": 1, "f8e3m4": 1, "f8e8m0fnu": 1,
    "s16": 2, "u16": 2, "f16": 2, "bf16": 2,
    "s32": 4, "u32": 4, "f32": 4,
    "s64": 8, "u64": 8, "f64": 8, "c64": 8,
    "c128": 16,
}

# one collective instruction: "%name = <result shapes> op(" or "op-start("
_COLLECTIVE_RE = re.compile(
    r"=\s*(?P<shape>\(?[^=()]*?\)?)\s*"
    r"(?P<op>" + "|".join(re.escape(op) for op in COLLECTIVE_OPS) + r")"
    r"(?P<start>-start)?\("
)
_SHAPE_RE = re.compile(r"(?P<dtype>[a-z]\w*)\[(?P<dims>[0-9,]*)\]")


def _hlo_text(compiled_or_text: Any) -> str:
    if isinstance(compiled_or_text, str):
        return compiled_or_text
    return compiled_or_text.as_text()


def _shape_sizes(shape_text: str) -> List[int]:
    """Byte size of each array in an HLO shape fragment, in order
    (token/opaque and unknown dtypes contribute nothing)."""
    out = []
    for m in _SHAPE_RE.finditer(shape_text):
        width = _DTYPE_BYTES.get(m.group("dtype"))
        if width is None:
            continue
        dims = m.group("dims")
        out.append(width * (math.prod(int(d) for d in dims.split(","))
                            if dims else 1))
    return out


def _result_bytes(shape_text: str, is_start: bool) -> int:
    """Result-byte volume of one collective's printed shape.

    Sync forms: the whole shape IS the result (variadic tuples summed).
    Async ``-start`` forms return ``(operand, result[, context...])`` —
    summing the tuple would double-count the aliased operand, making async
    (TPU) audits ~2x their sync (CPU) equivalents.  We take the LAST array
    after dropping scalar context buffers (u32[] etc.); variadic async
    collectives (rare) are under- rather than double-counted."""
    sizes = _shape_sizes(shape_text)
    if not sizes:
        return 0
    if not is_start or len(sizes) == 1:
        return sum(sizes)
    # drop trailing scalar context buffers (u32[] handles, <= 8 bytes each),
    # then take the result element — the last remaining array
    trimmed = list(sizes)
    while len(trimmed) > 2 and trimmed[-1] <= 8:
        trimmed.pop()
    return trimmed[-1]


def collective_counts(hlo_text: str) -> Dict[str, int]:
    """Count each collective op kind (async ``-start`` forms count once; the
    matching ``-done`` carries no shape work and is not matched)."""
    counts = {op: 0 for op in COLLECTIVE_OPS}
    for m in _COLLECTIVE_RE.finditer(hlo_text):
        counts[m.group("op")] += 1
    return counts


def collective_bytes(hlo_text: str) -> Dict[str, int]:
    """Sum the result-shape byte volume per collective op kind (async
    ``-start`` forms contribute their result element only, so sync and
    async compilations of the same program report comparable volumes)."""
    out = {op: 0 for op in COLLECTIVE_OPS}
    for m in _COLLECTIVE_RE.finditer(hlo_text):
        out[m.group("op")] += _result_bytes(
            m.group("shape"), m.group("start") is not None)
    return out


def comm_audit(compiled_or_text: Any, name: str = "program") -> dict:
    """One audit record for a compiled executable (or raw HLO text):
    collective counts + byte volumes, merged with the XLA cost analysis
    (:func:`~..utils.profiling.cost_report`) when a real executable is
    given."""
    txt = _hlo_text(compiled_or_text)
    counts = collective_counts(txt)
    volumes = collective_bytes(txt)
    rec = {
        "schema": HLO_AUDIT_SCHEMA,
        "name": name,
        "time": time.time(),
        "collective_counts": counts,
        "collective_bytes": volumes,
        "total_collective_count": sum(counts.values()),
        "total_collective_bytes": sum(volumes.values()),
    }
    if not isinstance(compiled_or_text, str):
        try:
            rec["cost"] = cost_report(compiled_or_text)
        except Exception:  # pragma: no cover - backend-dependent
            rec["cost"] = {}
    return rec


def append_audit(path: str, record: dict) -> None:
    with open(path, "a") as f:
        f.write(json.dumps(record) + "\n")


def read_audits(path: str) -> List[dict]:
    out = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if line:
                out.append(json.loads(line))
    return out
