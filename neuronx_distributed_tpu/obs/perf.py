"""Per-phase performance attribution: roofline, device-time accounting, MFU.

PRs 11-13 built the sensors — request traces say ``decode_step`` took
4.1 ms, the compile ledger says the program moves N bytes and F flops —
but nothing joined them.  :class:`PerfAttribution` is that join: per
phase-fn family (``prefill`` / ``prefill_chunk`` / ``decode_step`` /
``spec_round`` / ``train_step``) it accounts device wall-time and call
counts on the hot path, takes per-call flops/bytes from the compile
ledger's cost extras (:func:`~..utils.profiling.cost_report`), and
classifies each family against a :class:`DeviceSpec` roofline — achieved
FLOP/s, achieved bytes/s, arithmetic intensity, compute- vs memory-bound,
percent-of-roofline — plus an MFU/MBU rollup for training and a
tokens/s-ceiling rollup for serving.

Allocation discipline mirrors ``SPANS_CREATED`` / ``LEDGER_ROWS``: the
module-level :data:`PERF_RECORDS` counter increments on every per-family
accumulator and attribution record this module allocates, every call site
guards on ``perf is not None``, and the zero-allocation-when-off test
asserts the counter never moves over a full run with ``perf=False``.

The device table is a deterministic cost model: known TPU kinds carry
published peak FLOP/s + HBM bandwidth; on CPU (the test mesh) the spec is
calibrated once per process from a fixed micro-workload and cached, so
every record in a run classifies against the same numbers and the CPU
tunnel is never the blocker for exercising the attribution path.
"""

from __future__ import annotations

import dataclasses
import json
import os
import time
from typing import Any, Dict, Iterable, List, Optional, Tuple

PERF_ATTRIBUTION_FILE = "perf_attribution.jsonl"
PERF_ATTRIBUTION_SCHEMA = "perf_attribution/1"

# phase-fn families the serving engine + trainer account device time for
PERF_FAMILIES = ("prefill", "prefill_chunk", "decode_step", "spec_round",
                 "train_step")

# compiled-program family -> phase family: the ledger books costs per
# PROGRAM (``prefill_one``, ``write_page``, ...) while device time is
# accounted per PHASE — this map is the join.  A phase executes several
# programs (a paged prefill runs prefill_one once and write_page per
# page), so phase flops are the sum over its programs of per-call cost x
# executions (the _CompiledLRU feeds executions via note_program_call).
PHASE_PROGRAMS: Dict[str, Tuple[str, ...]] = {
    "prefill": ("prefill_one", "prefill_one_lora", "insert_slot",
                "insert_valid", "write_page", "copy_page",
                "write_adapter_page"),
    "prefill_chunk": ("prefill_chunk_pages",),
    "decode_step": ("decode_slots", "decode_pages", "decode_pages_lora",
                    "jit:sample_rows", "jit:pack_tokens"),
    "spec_round": ("verify_pages",),
    "train_step": ("train_step",),
}
_PROGRAM_PHASE: Dict[str, str] = {
    prog: phase for phase, progs in PHASE_PROGRAMS.items() for prog in progs
}

# every per-family accumulator / attribution record allocated by this
# module bumps this counter — tests assert it stays flat with perf off
# (the SPANS_CREATED / LEDGER_ROWS discipline)
PERF_RECORDS = 0

# ms-scale histogram boundaries (mirrors obs.MS_BUCKETS; duplicated here
# because the obs package imports this module at init time)
_MS_BUCKETS = (1.0, 2.5, 5.0, 10.0, 25.0, 50.0, 100.0, 250.0, 500.0,
               1000.0, 2500.0, 5000.0, 10000.0, 30000.0)


@dataclasses.dataclass(frozen=True)
class DeviceSpec:
    """Peak compute + HBM bandwidth for one device kind — the two numbers
    a roofline needs.  ``kind`` is a lowercase prefix of jax's
    ``device.device_kind`` (the :func:`~bench.peak_flops_for` idiom)."""

    kind: str
    peak_flops: float
    hbm_bytes_per_s: float


# Published bf16 peak FLOP/s + HBM BW per chip.  Longest prefix wins, so
# "tpu v5 lite" (v5e) is matched before the bare "tpu v5" (v5p) entry.
DEVICE_SPECS: Tuple[DeviceSpec, ...] = (
    DeviceSpec("tpu v6 lite", 918e12, 1640e9),   # v6e / Trillium
    DeviceSpec("tpu v5 lite", 197e12, 819e9),    # v5e
    DeviceSpec("tpu v5e", 197e12, 819e9),
    DeviceSpec("tpu v5", 459e12, 2765e9),        # v5p
    DeviceSpec("tpu v4", 275e12, 1228e9),
)

_CPU_SPEC: Optional[DeviceSpec] = None


def calibrate_cpu_spec() -> DeviceSpec:
    """Calibrate-on-first-use CPU spec: one fixed matmul + one fixed copy,
    measured once per process and cached, so every classification in a
    run (and every test) sees the same numbers.  The result is a cost
    MODEL for the test mesh, not a claim about the host."""
    global _CPU_SPEC
    if _CPU_SPEC is not None:
        return _CPU_SPEC
    import numpy as np

    n = 256
    a = np.ones((n, n), np.float32)
    b = np.ones((n, n), np.float32)
    a @ b  # warm BLAS dispatch
    peak = 0.0
    for _ in range(3):
        t0 = time.perf_counter()
        a @ b
        peak = max(peak, 2.0 * n ** 3 / max(time.perf_counter() - t0, 1e-9))
    src = np.ones(4 << 20, np.uint8)
    dst = np.empty_like(src)
    np.copyto(dst, src)  # warm
    bw = 0.0
    for _ in range(3):
        t0 = time.perf_counter()
        np.copyto(dst, src)
        # read + write of the buffer per copy
        bw = max(bw, 2.0 * src.nbytes / max(time.perf_counter() - t0, 1e-9))
    _CPU_SPEC = DeviceSpec("cpu", max(peak, 1e9), max(bw, 1e9))
    return _CPU_SPEC


def device_spec(device: Any = None) -> DeviceSpec:
    """Resolve the :class:`DeviceSpec` for ``device`` (default: the first
    jax device).  Unknown kinds fall back to the calibrated CPU spec."""
    kind = None
    if device is None:
        try:
            import jax

            device = jax.devices()[0]
        except Exception:  # noqa: BLE001 — spec lookup must never raise
            device = None
    if device is not None:
        kind = str(getattr(device, "device_kind", None)
                   or getattr(device, "platform", "cpu")).lower()
    if kind:
        for spec in sorted(DEVICE_SPECS, key=lambda s: -len(s.kind)):
            if kind.startswith(spec.kind):
                return spec
    return calibrate_cpu_spec()


def roofline_attribution(
    family: str,
    calls: float,
    device_ms: float,
    flops: float,
    bytes_accessed: float,
    spec: DeviceSpec,
    *,
    now: Optional[float] = None,
    mono: Optional[float] = None,
) -> dict:
    """One attribution record from TOTAL flops/bytes over ``calls``
    executions taking ``device_ms`` of device wall-time.

    ``pct_roofline`` is ``lower_bound / achieved`` — 1.0 means the family
    runs at the roofline, 0.1 means 10x off it; ``bound`` is which wall
    it would hit first.  ``mfu`` / ``mbu`` are the achieved fractions of
    peak compute / bandwidth."""
    wall_s = max(device_ms, 0.0) / 1e3
    t_compute = flops / spec.peak_flops if spec.peak_flops else 0.0
    t_memory = (bytes_accessed / spec.hbm_bytes_per_s
                if spec.hbm_bytes_per_s else 0.0)
    lower = max(t_compute, t_memory)
    safe_wall = max(wall_s, 1e-12)
    rec = {
        "schema": PERF_ATTRIBUTION_SCHEMA,
        "family": family,
        "calls": float(calls),
        "device_ms": round(device_ms, 4),
        "flops": float(flops),
        "bytes": float(bytes_accessed),
        "flops_per_s": flops / safe_wall if wall_s > 0 else 0.0,
        "bytes_per_s": bytes_accessed / safe_wall if wall_s > 0 else 0.0,
        "arithmetic_intensity": (flops / bytes_accessed
                                 if bytes_accessed else None),
        "bound": "compute" if t_compute >= t_memory else "memory",
        "lower_bound_ms": lower * 1e3,
        "pct_roofline": (lower / safe_wall) if wall_s > 0 else 0.0,
        "mfu": (flops / safe_wall / spec.peak_flops)
        if wall_s > 0 and spec.peak_flops else 0.0,
        "mbu": (bytes_accessed / safe_wall / spec.hbm_bytes_per_s)
        if wall_s > 0 and spec.hbm_bytes_per_s else 0.0,
        "device": spec.kind,
        "peak_flops": spec.peak_flops,
        "hbm_bytes_per_s": spec.hbm_bytes_per_s,
        "time": time.time() if now is None else now,
        "mono": time.monotonic() if mono is None else mono,
    }
    return rec


def attribute(
    family: str,
    calls: float,
    device_ms: float,
    flops_per_call: float,
    bytes_per_call: float,
    spec: DeviceSpec,
    **kw,
) -> dict:
    """Per-call-cost convenience wrapper over
    :func:`roofline_attribution`."""
    return roofline_attribution(
        family, calls, device_ms, calls * flops_per_call,
        calls * bytes_per_call, spec, **kw)


class PerfAttribution:
    """The live accounting object ``fit()`` and the serving engine drive.

    Hot-path API (allocation-free after the first call per family):

    - :meth:`note_phase` — device wall-time + call count per family,
      stamped with the SAME clock deltas as the tracer's spans so the
      attribution sums to the traced wall-time;
    - :meth:`note_tokens` — committed tokens (serving ceiling rollup).

    Join API (warm path / read side):

    - :meth:`note_cost` — explicit per-call flops/bytes for a family;
    - :meth:`ingest_ledger` — per-call costs from a
      :class:`~.compile_ledger.CompileLedger`'s cost extras;
    - :meth:`ingest_spans` — device time from finished tracer spans
      (offline attribution of a trace another process recorded);
    - :meth:`attribution` / :meth:`rollup` / :meth:`dump` — the records.
    """

    def __init__(
        self,
        path: Optional[str] = None,
        registry: Any = None,
        spec: Optional[DeviceSpec] = None,
        device: Any = None,
        ledger: Any = None,
        clock=time.monotonic,
    ):
        self.path = path
        self.registry = registry
        self.spec = spec if spec is not None else device_spec(device)
        self._ledger = ledger
        self._clock = clock
        # family -> [calls, device_ms]
        self._fams: Dict[str, List[float]] = {}
        # family -> (flops_per_call, bytes_per_call) from note_cost; an
        # explicit per-call cost wins over the ledger join for that family
        self._costs: Dict[str, Tuple[float, float]] = {}
        # compiled-program family -> executions.  The _CompiledLRU feeds
        # this on every cache hit and first call while perf is attached;
        # mark_warmup_done() snapshots a baseline so warm-pass executions
        # stay out of the measured attribution.
        self._prog_calls: Dict[str, float] = {}
        self._prog_base: Dict[str, float] = {}
        # phase family -> (total flops, total bytes): rebuilt by
        # ingest_ledger as sum over the phase's programs of
        # per-call cost (mean across compile rows) x executions
        self._ledger_totals: Dict[str, Tuple[float, float]] = {}
        self._tokens = 0.0

    def attach(self, registry: Any = None, ledger: Any = None) -> None:
        """Fill in sinks not known at construction (an engine attaches its
        registry / compile ledger to a caller-provided layer).  Only empty
        slots are filled — explicit construction wins (the
        :meth:`CompileLedger.attach <..compile_ledger.CompileLedger.attach>`
        convention)."""
        if self.registry is None:
            self.registry = registry
        if self._ledger is None:
            self._ledger = ledger

    # -- hot path ----------------------------------------------------------

    def note_phase(self, family: str, device_ms: float,
                   calls: float = 1.0) -> None:
        """Account ``device_ms`` of device wall-time (and ``calls``
        executions) to ``family``.  Call sites pass the same clock deltas
        they stamp tracer spans with, so per-family sums match the trace."""
        global PERF_RECORDS
        acc = self._fams.get(family)
        if acc is None:
            PERF_RECORDS += 1
            acc = self._fams[family] = [0.0, 0.0]
        acc[0] += calls
        acc[1] += device_ms
        if self.registry is not None:
            self.registry.histogram(
                f"perf/{family}_device_ms", _MS_BUCKETS).observe(device_ms)

    def note_tokens(self, n: float) -> None:
        """Account ``n`` committed tokens (serving tokens/s ceiling)."""
        self._tokens += n

    def note_program_call(self, program: str) -> None:
        """Count one execution of a compiled program family.  The
        ``_CompiledLRU`` calls this on every cache hit and first call, so
        executions = hits + compiles without touching the ledger."""
        global PERF_RECORDS
        if program not in self._prog_calls:
            PERF_RECORDS += 1
            self._prog_calls[program] = 0.0
        self._prog_calls[program] += 1.0

    def mark_warmup_done(self) -> None:
        """Snapshot program-execution counters: executions before this
        point (the warm pass compiles and smoke calls) are excluded from
        the cost join, matching phase accounting which only covers the
        measured window."""
        self._prog_base = dict(self._prog_calls)

    # -- cost join ---------------------------------------------------------

    def note_cost(self, family: str, flops: float,
                  bytes_accessed: float) -> None:
        """Explicit per-call cost for a family (e.g. the trainer's
        model-flops accounting when no compiled cost report exists)."""
        self._costs[family] = (float(flops), float(bytes_accessed))

    def ingest_ledger(self, ledger: Any = None) -> int:
        """Join compile-ledger cost extras onto phase families.  Ledger
        rows carry costs per compiled PROGRAM (``prefill_one``,
        ``write_page``, ...); a phase executes several programs, so per
        phase the total is the sum over its programs of per-call cost
        (mean across that program's compile rows — keys differ by shape)
        times executions counted by :meth:`note_program_call`.  Rebuilt
        from scratch on every call (counters keep moving between calls).
        Returns the number of phase families holding a ledger total."""
        ledger = ledger if ledger is not None else self._ledger
        if ledger is None:
            return 0
        rows = getattr(ledger, "rows", None) or []
        sums: Dict[str, List[float]] = {}
        for row in rows:
            if row.get("event") != "compile":
                continue
            fl = row.get("flops")
            by = row.get("bytes_accessed")
            if fl is None and by is None:
                continue
            s = sums.setdefault(row["family"], [0.0, 0.0, 0.0])
            s[0] += float(fl or 0.0)
            s[1] += float(by or 0.0)
            s[2] += 1.0
        totals: Dict[str, List[float]] = {}
        for prog, (fl, by, n) in sums.items():
            phase = _PROGRAM_PHASE.get(prog)
            if phase is None or phase in self._costs:
                continue
            calls = (self._prog_calls.get(prog, 0.0)
                     - self._prog_base.get(prog, 0.0))
            if calls <= 0.0 and prog == phase and phase in self._fams:
                # program == phase 1:1 (train_step) runs outside any
                # _CompiledLRU — every accounted phase call executed it
                calls = self._fams[phase][0]
            if calls <= 0.0:
                continue
            t = totals.setdefault(phase, [0.0, 0.0])
            t[0] += (fl / n) * calls
            t[1] += (by / n) * calls
        self._ledger_totals = {k: (v[0], v[1]) for k, v in totals.items()}
        return len(self._ledger_totals)

    def ingest_spans(self, spans: Iterable[Any],
                     families: Tuple[str, ...] = PERF_FAMILIES) -> int:
        """Offline accounting: fold finished tracer spans (Span objects or
        ``trace_event`` records) whose name is a known family into the
        per-family device time.  Returns the span count ingested."""
        n = 0
        for s in spans:
            if isinstance(s, dict):
                name = s.get("name")
                dur = (s.get("t_end", 0.0) - s.get("t_start", 0.0)) * 1e3
            else:
                name = getattr(s, "name", None)
                dur = getattr(s, "duration_ms", 0.0)
            if name in families:
                self.note_phase(name, dur)
                n += 1
        return n

    # -- read side ---------------------------------------------------------

    def attribution(self) -> List[dict]:
        """One attribution record per family plus a ``_total`` rollup
        record (summed device time / flops / bytes; its lower bound is the
        SUM of per-family lower bounds — phases run sequentially — and its
        extras carry the committed tokens + tokens/s ceiling)."""
        global PERF_RECORDS
        self.ingest_ledger()
        now, mono = time.time(), time.monotonic()
        recs: List[dict] = []
        tot_f = tot_b = tot_ms = tot_calls = 0.0
        tot_tc = tot_tm = 0.0
        for family in sorted(self._fams):
            calls, ms = self._fams[family]
            if family in self._costs:
                # explicit note_cost: per-call flops/bytes x calls
                fl_pc, by_pc = self._costs[family]
                rec = attribute(family, calls, ms, fl_pc, by_pc,
                                self.spec, now=now, mono=mono)
            else:
                # ledger join: phase TOTALS (programs x executions)
                fl, by = self._ledger_totals.get(family, (0.0, 0.0))
                rec = roofline_attribution(family, calls, ms, fl, by,
                                           self.spec, now=now, mono=mono)
            recs.append(rec)
            tot_f += rec["flops"]
            tot_b += rec["bytes"]
            tot_ms += rec["device_ms"]
            tot_calls += calls
            tot_tc += rec["flops"] / self.spec.peak_flops
            tot_tm += rec["bytes"] / self.spec.hbm_bytes_per_s
        if recs:
            total = roofline_attribution("_total", tot_calls, tot_ms,
                                         tot_f, tot_b, self.spec,
                                         now=now, mono=mono)
            # sequential phases: the total's floor is the sum of floors
            lower_s = sum(
                max(r["flops"] / self.spec.peak_flops,
                    r["bytes"] / self.spec.hbm_bytes_per_s) for r in recs)
            total["lower_bound_ms"] = lower_s * 1e3
            total["pct_roofline"] = (lower_s / (tot_ms / 1e3)
                                     if tot_ms > 0 else 0.0)
            total["bound"] = "compute" if tot_tc >= tot_tm else "memory"
            total["tokens"] = self._tokens
            total["toks_per_s_ceiling"] = (
                self._tokens / lower_s if self._tokens and lower_s > 0
                else None)
            recs.append(total)
        PERF_RECORDS += len(recs)
        return recs

    def rollup(self) -> Optional[dict]:
        """The headline numbers: MFU/MBU over everything accounted, the
        total percent-of-roofline, and (when tokens were committed) the
        tokens/s ceiling.  None before any phase was accounted."""
        recs = self.attribution()
        if not recs:
            return None
        total = recs[-1]
        return {
            "device": total["device"],
            "families": len(recs) - 1,
            "device_ms": total["device_ms"],
            "mfu": total["mfu"],
            "mbu": total["mbu"],
            "pct_roofline": total["pct_roofline"],
            "bound": total["bound"],
            "tokens": total.get("tokens", 0.0),
            "toks_per_s_ceiling": total.get("toks_per_s_ceiling"),
        }

    def update_metrics(self) -> None:
        """Refresh the ``perf/*`` registry gauges from the current rollup
        (milli-units: gauges are plain floats, MFU is a 0..1 fraction).
        Called on the observe cadence, not per phase — the rollup walks
        every family."""
        if self.registry is None:
            return
        roll = self.rollup()
        if roll is None:
            return
        self.registry.gauge("perf/mfu_milli").set(roll["mfu"] * 1e3)
        self.registry.gauge("perf/mbu_milli").set(roll["mbu"] * 1e3)
        self.registry.gauge("perf/roofline_pct_milli").set(
            roll["pct_roofline"] * 1e3)

    def dump(self, path: Optional[str] = None) -> Optional[str]:
        """Write the attribution records as ``perf_attribution.jsonl``.
        Returns the path, or None when nothing was accounted."""
        path = path or self.path
        recs = self.attribution()
        if path is None or not recs:
            return None
        os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
        with open(path, "w") as f:
            for r in recs:
                f.write(json.dumps(r) + "\n")
        return path


def read_perf_attribution(path: str) -> List[dict]:
    """Read a ``perf_attribution.jsonl`` artifact."""
    out: List[dict] = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if line:
                out.append(json.loads(line))
    return out


def summarize_perf(records: Iterable[dict]) -> Optional[dict]:
    """The obs-report ``perf`` section from attribution records: per-family
    table rows (sorted by device time, the top time-eaters first) plus the
    ``_total`` rollup.  None when there are no records."""
    fams: List[dict] = []
    total: Optional[dict] = None
    for r in records:
        if r.get("family") == "_total":
            total = r
        else:
            fams.append(r)
    if not fams and total is None:
        return None
    fams.sort(key=lambda r: -r.get("device_ms", 0.0))
    section = {
        "device": (total or fams[0])["device"],
        "families": {
            r["family"]: {
                "calls": r["calls"],
                "device_ms": r["device_ms"],
                "flops": r["flops"],
                "bytes": r["bytes"],
                "arithmetic_intensity": r["arithmetic_intensity"],
                "bound": r["bound"],
                "pct_roofline": round(r["pct_roofline"], 6),
                "mfu": round(r["mfu"], 6),
                "mbu": round(r["mbu"], 6),
            }
            for r in fams
        },
        "top_time_eaters": [r["family"] for r in fams[:5]],
    }
    if total is not None:
        section["rollup"] = {
            "device_ms": total["device_ms"],
            "mfu": round(total["mfu"], 6),
            "mbu": round(total["mbu"], 6),
            "pct_roofline": round(total["pct_roofline"], 6),
            "bound": total["bound"],
            "tokens": total.get("tokens", 0.0),
            "toks_per_s_ceiling": total.get("toks_per_s_ceiling"),
        }
    return section


def merge_perf_records(streams: Iterable[Iterable[dict]]) -> List[dict]:
    """Fleet merge: sum each family's calls / device time / flops / bytes
    across replicas and recompute the derived roofline numbers against the
    first stream's device spec; ``_total`` rollups merge the same way
    (tokens sum, ceiling recomputed)."""
    fams: Dict[str, List[float]] = {}
    spec: Optional[DeviceSpec] = None
    tokens = 0.0
    for stream in streams:
        for r in stream:
            if spec is None:
                spec = DeviceSpec(r["device"], r["peak_flops"],
                                  r["hbm_bytes_per_s"])
            if r.get("family") == "_total":
                tokens += r.get("tokens", 0.0) or 0.0
                continue
            s = fams.setdefault(r["family"], [0.0, 0.0, 0.0, 0.0])
            s[0] += r.get("calls", 0.0)
            s[1] += r.get("device_ms", 0.0)
            s[2] += r.get("flops", 0.0)
            s[3] += r.get("bytes", 0.0)
    if spec is None:
        return []
    now, mono = time.time(), time.monotonic()
    out = [
        roofline_attribution(fam, c, ms, fl, by, spec, now=now, mono=mono)
        for fam, (c, ms, fl, by) in sorted(fams.items())
    ]
    if out:
        tot_f = sum(r["flops"] for r in out)
        tot_b = sum(r["bytes"] for r in out)
        tot_ms = sum(r["device_ms"] for r in out)
        tot_calls = sum(r["calls"] for r in out)
        total = roofline_attribution("_total", tot_calls, tot_ms, tot_f,
                                     tot_b, spec, now=now, mono=mono)
        lower_s = sum(max(r["flops"] / spec.peak_flops,
                          r["bytes"] / spec.hbm_bytes_per_s) for r in out)
        total["lower_bound_ms"] = lower_s * 1e3
        total["pct_roofline"] = (lower_s / (tot_ms / 1e3)
                                 if tot_ms > 0 else 0.0)
        total["tokens"] = tokens
        total["toks_per_s_ceiling"] = (tokens / lower_s
                                       if tokens and lower_s > 0 else None)
        out.append(total)
    return out


__all__ = [
    "DeviceSpec",
    "DEVICE_SPECS",
    "PERF_ATTRIBUTION_FILE",
    "PERF_ATTRIBUTION_SCHEMA",
    "PERF_FAMILIES",
    "PERF_RECORDS",
    "PHASE_PROGRAMS",
    "PerfAttribution",
    "attribute",
    "calibrate_cpu_spec",
    "device_spec",
    "merge_perf_records",
    "read_perf_attribution",
    "roofline_attribution",
    "summarize_perf",
]
