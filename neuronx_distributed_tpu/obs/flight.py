"""Step flight recorder + anomaly detectors.

A ring buffer of the last K step records — loss, grad-norm, step-time
breakdown (host dispatch vs device wait via ``block_until_ready`` timing,
data-loader stall) — that dumps to ``flight_record.json`` when the run dies
(crash or SIGTERM, hooked into ``fit()``'s existing signal path) and at
clean exit.  The rounds 3-5 bench post-mortems were reconstructed by hand
from scrollback (docs/BENCH_NOTES_r5.md); this makes the last K steps a
persisted artifact instead.

Detectors run synchronously on every record (they are a few float
comparisons) and emit three-way: a structured warning record (persisted in
the dump), a ``logger.warning``, and — when a timeline is attached — an
``instant()`` marker so the anomaly is visible in the Perfetto trace at the
step where it fired.
"""

from __future__ import annotations

import json
import math
import os
import statistics
import time
from collections import deque
from typing import Any, Deque, List, Optional

from neuronx_distributed_tpu.utils.logger import get_logger

logger = get_logger(__name__)

FLIGHT_SCHEMA = "flight_record_v1"
MAX_WARNINGS = 256


class AnomalyDetector:
    """Base detector: ``check(record, history)`` returns a message string
    when the anomaly fires, else None.  ``history`` is the ring content
    BEFORE ``record`` (oldest first)."""

    name = "anomaly"

    def check(self, record: dict, history: "Deque[dict]") -> Optional[str]:
        raise NotImplementedError


class NanLossDetector(AnomalyDetector):
    """Fires when the watched field is NaN/Inf — the canonical
    dead-run signature (the reference's runs die silently on this;
    SURVEY §5.5)."""

    name = "nan_loss"

    def __init__(self, field: str = "loss"):
        self.field = field

    def check(self, record, history):
        v = record.get(self.field)
        if v is not None and not math.isfinite(float(v)):
            return f"{self.field} is non-finite ({v!r})"
        return None


class LossSpikeDetector(AnomalyDetector):
    """Z-score of the current loss against the trailing window; fires on
    ``z > threshold`` once enough history exists.  A spike that large with a
    healthy data pipeline usually means a bad batch or an optimizer blow-up
    — worth a marker even when the run survives."""

    name = "loss_spike"

    def __init__(self, field: str = "loss", window: int = 32,
                 z_threshold: float = 6.0, min_history: int = 8):
        self.field = field
        self.window = window
        self.z_threshold = z_threshold
        self.min_history = min_history

    def check(self, record, history):
        v = record.get(self.field)
        if v is None or not math.isfinite(float(v)):
            return None  # NanLossDetector's jurisdiction
        past = [float(r[self.field]) for r in list(history)[-self.window:]
                if r.get(self.field) is not None
                and math.isfinite(float(r[self.field]))]
        if len(past) < self.min_history:
            return None
        mean = statistics.fmean(past)
        std = statistics.pstdev(past)
        # the std floor keeps a flat-loss window (std ~ 0) from firing on
        # harmless jitter: require an absolute move too
        z = (float(v) - mean) / max(std, 1e-3 * max(abs(mean), 1e-9), 1e-12)
        if z > self.z_threshold:
            return (f"{self.field} spike: {float(v):.6g} vs window "
                    f"mean {mean:.6g} (z={z:.1f})")
        return None


class ThroughputRegressionDetector(AnomalyDetector):
    """Fires when a step takes ``factor``x the trailing-window median step
    time — the host-side signature of a data stall, a recompile, or a
    neighbor stealing the chip.  ``min_excess_s`` is an absolute floor on
    the slowdown: sub-second relative jitter on tiny (dev/CPU) steps is
    noise, while the stalls worth a marker cost whole seconds."""

    name = "throughput_regression"

    def __init__(self, field: str = "step_time_s", window: int = 32,
                 factor: float = 3.0, min_history: int = 8,
                 min_excess_s: float = 0.25):
        self.field = field
        self.window = window
        self.factor = factor
        self.min_history = min_history
        self.min_excess_s = min_excess_s

    def check(self, record, history):
        v = record.get(self.field)
        if v is None:
            return None
        past = [float(r[self.field]) for r in list(history)[-self.window:]
                if r.get(self.field) is not None]
        if len(past) < self.min_history:
            return None
        med = statistics.median(past)
        if med > 0 and float(v) > self.factor * med \
                and float(v) - med > self.min_excess_s:
            return (f"step took {float(v) * 1e3:.1f} ms vs trailing median "
                    f"{med * 1e3:.1f} ms ({float(v) / med:.1f}x)")
        return None


def default_detectors() -> List[AnomalyDetector]:
    return [NanLossDetector(), LossSpikeDetector(), ThroughputRegressionDetector()]


def _json_safe(obj):
    """Strict-JSON view: non-finite floats become strings ("NaN"/"Inf"/
    "-Inf") so the dumped artifact parses under every JSON implementation,
    not just Python's NaN-tolerant one."""
    if isinstance(obj, float) and not math.isfinite(obj):
        return "NaN" if math.isnan(obj) else ("Inf" if obj > 0 else "-Inf")
    if isinstance(obj, dict):
        return {k: _json_safe(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [_json_safe(v) for v in obj]
    return obj


class FlightRecorder:
    """Ring buffer of step records with synchronous anomaly detection.

    ``record(step, **fields)`` appends one record and returns the warnings
    raised for it; ``dump(reason)`` atomically writes the whole ring (plus
    every warning so far) to ``flight_record.json``."""

    def __init__(
        self,
        capacity: int = 256,
        path: Optional[str] = None,
        detectors: Optional[List[AnomalyDetector]] = None,
        timeline: Any = None,
        registry: Any = None,
    ):
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        self.capacity = capacity
        self.path = path
        self.detectors = list(detectors) if detectors is not None else []
        self.timeline = timeline
        self.registry = registry
        self.records: Deque[dict] = deque(maxlen=capacity)
        self.warnings: Deque[dict] = deque(maxlen=MAX_WARNINGS)
        self.steps_recorded = 0

    def record(self, step: int, **fields) -> List[dict]:
        rec = {"step": int(step), "time": time.time()}
        for k, v in fields.items():
            if v is not None:
                rec[k] = float(v) if isinstance(v, (int, float)) else v
        fired: List[dict] = []
        for det in self.detectors:
            try:
                msg = det.check(rec, self.records)
            except Exception as e:  # a broken detector must not kill training
                logger.warning("flight: detector %s raised %r", det.name, e)
                continue
            if msg:
                warning = {
                    "step": int(step),
                    "detector": det.name,
                    "message": msg,
                    "value": rec.get(getattr(det, "field", "loss")),
                    "time": rec["time"],
                }
                fired.append(warning)
                self.warnings.append(warning)
                logger.warning("flight anomaly [%s] step %d: %s",
                               det.name, step, msg)
                if self.registry is not None:
                    self.registry.counter("obs/anomalies_total").inc()
                    self.registry.counter(f"obs/anomalies/{det.name}").inc()
                if self.timeline is not None:
                    self.timeline.instant(
                        f"anomaly/{det.name}", step=int(step), message=msg)
        if fired:
            rec["anomalies"] = [w["detector"] for w in fired]
        self.records.append(rec)
        self.steps_recorded += 1
        return fired

    def dump(self, reason: str, path: Optional[str] = None) -> Optional[str]:
        """Write the ring (and accumulated warnings) as one JSON document;
        atomic (temp file + ``os.replace``) so a crash mid-dump can't leave
        a truncated artifact.  Returns the path written, or None when the
        recorder has no sink."""
        path = path or self.path
        if path is None:
            return None
        doc = {
            "schema": FLIGHT_SCHEMA,
            "reason": reason,
            "dumped_at": time.time(),
            "capacity": self.capacity,
            "steps_recorded": self.steps_recorded,
            "records": list(self.records),
            "warnings": list(self.warnings),
        }
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(_json_safe(doc), f, indent=1, allow_nan=False)
        os.replace(tmp, path)
        return path


def read_flight(path: str) -> dict:
    with open(path) as f:
        doc = json.load(f)
    if doc.get("schema") != FLIGHT_SCHEMA:
        raise ValueError(
            f"{path}: schema {doc.get('schema')!r} != {FLIGHT_SCHEMA!r}")
    return doc
