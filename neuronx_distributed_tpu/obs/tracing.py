"""Request-lifecycle distributed tracing + the Chrome-trace timeline writer.

Two consumers share this module:

- :class:`Tracer` / :class:`Span` — the serving stack's per-request span
  tracer (vLLM-style OpenTelemetry-shaped lifecycle spans: queue → prefill
  chunks → decode steps → preemption gaps → failover hops).  Monotonic-
  clocked, ring-bounded, ZERO overhead when no tracer is attached (the
  engine's hot paths guard every call site on ``tracer is not None``; the
  module-level :data:`SPANS_CREATED` counter is the test hook that proves
  no span is ever allocated with tracing off).  Two exporters: a
  schema-checked ``trace_events.jsonl`` (one record per span, stamped with
  BOTH wall-clock ``ts`` and monotonic ``mono`` so cross-replica merges
  sort correctly under clock skew) and a Chrome-trace / Perfetto JSON
  file (one track per replica, one row per request).

- :class:`Timeline` — the trainer's host-side Chrome-trace event recorder,
  historically ``utils/timeline.py`` (which is now a thin re-export of this
  module, so trainer callers are untouched).  Both writers share one
  Chrome-trace serialization (:func:`write_chrome_trace` /
  :func:`append_chrome_events`), so a trainer timeline and a serving trace
  open in the same Perfetto UI with the same conventions.

Span model: a span has a ``name``, the fleet-global ``request_id`` it
belongs to (-1 for batch-level spans like one engine decode step), the
``replica`` that produced it (-1 off-fleet), monotonic ``t_start``/
``t_end`` seconds, an optional ``parent_id``, and a free-form ``attrs``
dict.  A request's trace STITCHES across replicas by ``request_id``: a
failover clone keeps the original global id and its spans carry a ``hop``
attr, so one ``trace_events.jsonl`` holds exactly one trace per request no
matter how many replicas served it.
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from contextlib import contextmanager
from typing import Any, Dict, Iterable, List, Optional, Sequence

from neuronx_distributed_tpu.utils.logger import get_logger

logger = get_logger(__name__)

TRACE_EVENTS_FILE = "trace_events.jsonl"
TRACE_EVENT_SCHEMA = "trace_event/1"

# span phases the per-request waterfall is built from (obs.report): every
# other span name is informational detail underneath these
PHASE_NAMES = ("queue", "prefill", "decode", "preempted")

# module-level allocation counter: the tracer-off overhead test reads it
# around a full serving run and asserts it never moved — the "zero
# allocations in the hot path when off" contract, checkable without a
# profiler
SPANS_CREATED = 0


class Span:
    """One trace span.  Mutable until :meth:`Tracer.end` seals it into the
    ring; ``attrs`` is a plain dict serialized verbatim."""

    __slots__ = ("name", "span_id", "parent_id", "request_id", "replica",
                 "t_start", "t_end", "ts", "attrs")

    def __init__(self, name: str, span_id: int, parent_id: Optional[int],
                 request_id: int, replica: int, t_start: float, ts: float,
                 attrs: Dict[str, Any]):
        self.name = name
        self.span_id = span_id
        self.parent_id = parent_id
        self.request_id = request_id
        self.replica = replica
        self.t_start = t_start
        self.t_end: Optional[float] = None
        self.ts = ts
        self.attrs = attrs

    @property
    def duration_ms(self) -> Optional[float]:
        if self.t_end is None:
            return None
        return (self.t_end - self.t_start) * 1e3

    def to_record(self) -> dict:
        """The ``trace_events.jsonl`` record (``obs.schemas`` kind
        ``trace_event``): both clocks on every span — ``ts`` (wall, a
        shared epoch for cross-host merges) and ``mono`` (the monotonic
        start, skew-free ordering within a host)."""
        return {
            "schema": TRACE_EVENT_SCHEMA,
            "name": self.name,
            "span_id": self.span_id,
            "parent_id": self.parent_id,
            "request_id": self.request_id,
            "replica": self.replica,
            "t_start": self.t_start,
            "t_end": self.t_end if self.t_end is not None else self.t_start,
            "ts": self.ts,
            "mono": self.t_start,
            "attrs": self.attrs,
        }


class _TraceCore:
    """State shared by a :class:`Tracer` and its per-replica scopes: ONE
    ring, ONE span-id sequence, one pair of clocks."""

    __slots__ = ("spans", "capacity", "dropped", "seq", "lock", "clock",
                 "wall")

    def __init__(self, capacity: int, clock, wall):
        self.spans: deque = deque(maxlen=capacity)
        self.capacity = capacity
        self.dropped = 0
        self.seq = 0
        self.lock = threading.Lock()
        self.clock = clock
        self.wall = wall


class Tracer:
    """Ring-bounded span recorder.

    ``capacity`` bounds retained FINISHED spans (oldest dropped first, the
    flight-recorder discipline — a long-lived server's trace memory is a
    window, not a leak).  ``clock`` must be monotonic (span math never
    touches wall time); ``wall`` stamps each span's shared-epoch ``ts``.
    ``replica`` tags every span this handle creates; :meth:`scoped` derives
    a same-ring handle with a different replica tag, which is how one
    tracer follows a request across a whole in-process fleet.
    """

    def __init__(self, capacity: int = 65536, replica: int = -1,
                 clock=time.monotonic, wall=time.time, *, _core=None):
        if _core is None:
            if capacity < 1:
                raise ValueError(f"capacity must be >= 1, got {capacity}")
            _core = _TraceCore(capacity, clock, wall)
        self._core = _core
        self.replica = int(replica)

    def scoped(self, replica: int) -> "Tracer":
        """A handle over the SAME ring/sequence tagging spans with
        ``replica`` — hand one to each fleet replica's engine."""
        return Tracer(replica=replica, _core=self._core)

    # -- recording ---------------------------------------------------------

    def begin(self, name: str, request_id: int = -1,
              parent: "Optional[Span | int]" = None,
              t: Optional[float] = None, **attrs) -> Span:
        """Open a span (not yet in the ring — :meth:`end` seals it).
        ``t`` overrides the start instant (monotonic seconds) so adjacent
        phase spans can share one boundary timestamp exactly."""
        global SPANS_CREATED
        core = self._core
        with core.lock:
            core.seq += 1
            sid = core.seq
        SPANS_CREATED += 1
        pid = parent.span_id if isinstance(parent, Span) else parent
        return Span(name, sid, pid, int(request_id), self.replica,
                    core.clock() if t is None else t, core.wall(), attrs)

    def end(self, span: Optional[Span], t: Optional[float] = None,
            **attrs) -> Optional[Span]:
        """Seal a span into the ring (idempotent on None so call sites can
        ``tr.end(state.pop(...))`` without guards)."""
        if span is None:
            return None
        core = self._core
        span.t_end = core.clock() if t is None else t
        if span.t_end < span.t_start:  # clock injection misuse, not physics
            span.t_end = span.t_start
        if attrs:
            span.attrs.update(attrs)
        with core.lock:
            if len(core.spans) == core.capacity:
                core.dropped += 1
            core.spans.append(span)
        return span

    @contextmanager
    def span(self, name: str, request_id: int = -1,
             parent: "Optional[Span | int]" = None, **attrs):
        s = self.begin(name, request_id=request_id, parent=parent, **attrs)
        try:
            yield s
        finally:
            self.end(s)

    def instant(self, name: str, request_id: int = -1,
                parent: "Optional[Span | int]" = None,
                t: Optional[float] = None, **attrs) -> Span:
        """Zero-duration marker span."""
        s = self.begin(name, request_id=request_id, parent=parent, t=t,
                       **attrs)
        return self.end(s, t=s.t_start)

    # -- introspection -----------------------------------------------------

    def spans(self) -> List[Span]:
        """Finished spans, oldest first."""
        with self._core.lock:
            return list(self._core.spans)

    @property
    def dropped(self) -> int:
        return self._core.dropped

    def clear(self) -> None:
        with self._core.lock:
            self._core.spans.clear()
            self._core.dropped = 0

    # -- exporters ---------------------------------------------------------

    def export_jsonl(self, path: str) -> int:
        """Write one ``trace_event`` record per finished span; returns the
        record count.  The file is self-contained (overwrite, not append):
        a trace export is a snapshot artifact, like a flight dump."""
        spans = self.spans()
        if self.dropped:
            logger.warning(
                "tracing: ring dropped %d span(s) (capacity %d) — the "
                "exported trace window is truncated at the front",
                self.dropped, self._core.capacity)
        _ensure_parent_dir(path)
        with open(path, "w") as f:
            for s in spans:
                f.write(json.dumps(s.to_record()) + "\n")
        return len(spans)

    def export_chrome(self, path: str) -> int:
        """Write the Perfetto / ``chrome://tracing`` JSON view: pid =
        replica (one process track per replica), tid = request id (one row
        per request), complete "X" events on the monotonic clock."""
        spans = self.spans()
        events: List[dict] = []
        named: set = set()
        for s in spans:
            key = (s.replica, s.request_id)
            if key not in named:
                named.add(key)
                events.append({"ph": "M", "name": "thread_name",
                               "pid": s.replica, "tid": s.request_id & 0x7FFFFFFF,
                               "args": {"name": f"request {s.request_id}"}})
            events.append(span_to_chrome_event(s))
        for replica in sorted({s.replica for s in spans}):
            events.append({"ph": "M", "name": "process_name", "pid": replica,
                           "args": {"name": f"replica {replica}"
                                    if replica >= 0 else "serving"}})
        write_chrome_trace(path, events)
        return len(events)


def span_to_chrome_event(span: Span) -> dict:
    """One complete ("X") Chrome-trace event for a finished span."""
    t_end = span.t_end if span.t_end is not None else span.t_start
    return {
        "name": span.name,
        "cat": "serving",
        "ph": "X",
        "ts": span.t_start * 1e6,
        "dur": max(t_end - span.t_start, 0.0) * 1e6,
        "pid": span.replica,
        "tid": span.request_id & 0x7FFFFFFF,
        "args": {"request_id": span.request_id, "span_id": span.span_id,
                 "parent_id": span.parent_id, **span.attrs},
    }


def read_trace_events(path: str) -> List[dict]:
    """Parse a ``trace_events.jsonl`` file (blank lines skipped)."""
    out: List[dict] = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if line:
                out.append(json.loads(line))
    return out


# -- shared Chrome-trace serialization ---------------------------------------
#
# One writer discipline for both emitters (Timeline and Tracer): the
# Perfetto-tolerant JSON-array format — a "[" header, one object per line
# with a trailing comma, no closing bracket required — appendable without
# re-reading the file.

def _ensure_parent_dir(path: str) -> None:
    parent = os.path.dirname(os.path.abspath(path))
    if parent:
        os.makedirs(parent, exist_ok=True)


def append_chrome_events(path: str, events: Iterable[dict],
                         first_write: bool) -> None:
    """Append events to a Chrome-trace file, writing the array header on
    the first call."""
    with open(path, "w" if first_write else "a") as f:
        if first_write:
            f.write("[\n")
        for e in events:
            f.write(json.dumps(e) + ",\n")


def write_chrome_trace(path: str, events: Sequence[dict]) -> None:
    """Write a complete Chrome-trace file in one shot (overwrite)."""
    _ensure_parent_dir(path)
    append_chrome_events(path, events, first_write=True)


# -- trainer host timeline (historically utils/timeline.py) ------------------

def _process_index() -> int:
    try:
        import jax

        return jax.process_index()
    except Exception:  # jax-less tooling contexts
        return 0


def _process_count() -> int:
    try:
        import jax

        return jax.process_count()
    except Exception:
        return 1


class Timeline:
    """Buffered Chrome trace-event recorder (the trainer's host-side task
    timeline — scheduler steps, checkpoint waves, data stalls).

    Events are complete ("X") records with microsecond timestamps; flushes
    are explicit (``mark_step_end``) so the hot loop never touches the
    filesystem — the same discipline as the reference's step-end gather.
    Single-controller JAX has no per-rank gather: every process appends its
    own events tagged ``pid = process_index`` to its own file (or one file
    when single-process), which Perfetto merges natively.
    """

    def __init__(self, trace_file_path: Optional[str], category: str = "host"):
        self.category = category
        self.enabled = trace_file_path is not None
        self._open_events: dict = {}
        self._buffer: list = []
        self._lock = threading.Lock()
        self._wrote_header = False
        if self.enabled:
            # one file per process: multi-host jobs on a shared filesystem
            # must not clobber each other's traces
            if _process_count() > 1:
                root, ext = os.path.splitext(trace_file_path)
                trace_file_path = (
                    f"{root}.proc{_process_index()}{ext or '.json'}")
            _ensure_parent_dir(trace_file_path)
        self.path = trace_file_path

    @staticmethod
    def _now_us() -> float:
        # wall clock (not perf_counter): cross-host merges need a shared
        # epoch, and NTP-synced wall time is the best host-side option
        return time.time_ns() / 1e3

    def mark_event_start(self, name: str) -> None:
        if not self.enabled:
            return
        with self._lock:
            # key by (name, thread): same-named regions may run concurrently
            # on prefetch/worker threads
            self._open_events[(name, threading.get_ident())] = self._now_us()

    def mark_event_end(self, name: str) -> None:
        if not self.enabled:
            return
        tid = threading.get_ident()
        with self._lock:
            start = self._open_events.pop((name, tid), None)
            if start is None:
                logger.warning("timeline: end without start for %r", name)
                return
            self._buffer.append(
                {
                    "name": name,
                    "cat": self.category,
                    "ph": "X",
                    "ts": start,
                    "dur": self._now_us() - start,
                    "pid": _process_index(),
                    "tid": tid % 2**31,
                }
            )

    @contextmanager
    def event(self, name: str):
        self.mark_event_start(name)
        try:
            yield
        finally:
            self.mark_event_end(name)

    def instant(self, name: str, **args) -> None:
        """Zero-duration marker (e.g. 'step boundary')."""
        if not self.enabled:
            return
        with self._lock:
            self._buffer.append(
                {
                    "name": name,
                    "cat": self.category,
                    "ph": "i",
                    "s": "p",
                    "ts": self._now_us(),
                    "pid": _process_index(),
                    "tid": 0,
                    "args": args,
                }
            )

    def mark_step_end(self, step: Optional[int] = None) -> None:
        """Flush buffered events to the trace file (JSON-array format that
        Perfetto accepts without a closing bracket)."""
        if not self.enabled:
            return
        if step is not None:
            self.instant("step_end", step=step)
        with self._lock:
            events, self._buffer = self._buffer, []
            if not events:
                return
            append_chrome_events(self.path, events,
                                 first_write=not self._wrote_header)
            self._wrote_header = True


@contextmanager
def device_trace(log_dir: str):
    """Capture an XLA device profile (tensorboard xplane) for the enclosed
    region — the TPU-side replacement for the Neuron profiling tools the
    reference delegates to (SURVEY §5.1)."""
    import jax

    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()
