"""Fleet-wide metric aggregation: merge N per-replica telemetry streams
into one fleet-level view.

A fleet run scatters its evidence: every replica engine owns a
:class:`~.registry.MetricRegistry` (plus ``serving_stats.jsonl`` when
configured) and the router owns a third.  This module is the merge layer:

- :func:`merge_snapshots` — fold per-replica ``registry.snapshot()`` dicts
  into one, per REGISTRY_METRICS kind: counters and gauges SUM (a fleet's
  queue depth is the sum of its queues), the :data:`GAUGE_MAX` set takes
  the MAX (a watermark's fleet value is its worst replica), histograms
  merge bucket-wise — the merged histogram is exactly the histogram of the
  concatenated samples (property-tested);
- :func:`fleet_prometheus_text` — the replica-labeled Prometheus
  exposition (``name{replica="0"} v`` per replica + the unlabeled merged
  series), with ``# TYPE`` emitted ONCE per metric family — concatenating
  per-replica ``prometheus_text()`` outputs duplicates TYPE lines, which
  breaks real scrapers;
- :class:`FleetAggregator` — the live object ``/metrics?scope=fleet``
  renders from: label -> registry sources, snapshot/merge/expose;
- :class:`FleetHealth` — the fleet's control room: one fleet-level
  :class:`~.health.HealthMonitor` over the MERGED snapshot plus lazily
  created per-replica monitors, all streaming to ONE ``alerts.jsonl``;
  the router raises/clears the ``replica_down`` condition through it on
  failover/restart;
- :func:`merge_scalar_records` / :func:`merge_serving_stats` /
  :func:`discover_replica_dirs` — the offline half ``obs_report
  --run-dir`` uses to fold a fleet run's scattered artifacts into one
  report.
"""

from __future__ import annotations

import glob
import json
import os
import time
from typing import Any, Callable, Dict, Iterable, List, Optional, Sequence, Tuple

from neuronx_distributed_tpu.obs.health import (
    AlertSink,
    HealthMonitor,
    default_rules,
    healthz_doc,
)
from neuronx_distributed_tpu.utils.logger import get_logger

logger = get_logger(__name__)

# gauges whose fleet-level value is the WORST replica, not the sum:
# last-observation latencies and peak watermarks
GAUGE_MAX = frozenset({
    "serving/last_step_ms",
    "mem/device_peak_bytes",
    "mem/device_bytes_limit",
})


def metric_kind(name: str, value: Any) -> str:
    """``counter`` / ``gauge`` / ``histogram`` for a snapshot entry: the
    REGISTRY_METRICS declaration when present, else the repo naming
    convention (dict = histogram, ``*_total`` = counter, else gauge)."""
    from neuronx_distributed_tpu.obs.schemas import REGISTRY_METRICS

    if isinstance(value, dict):
        return "histogram"
    kind = REGISTRY_METRICS.get(name)
    if kind is not None:
        return kind
    return "counter" if name.endswith("_total") else "gauge"


def merge_histogram_summaries(hists: Sequence[dict]) -> dict:
    """Merge histogram snapshot entries (``{"count", "sum", "buckets"}``
    with cumulative bucket counts).  Cumulative counts add bucket-wise, so
    for same-boundary histograms (a homogeneous fleet by construction) the
    result IS the histogram of the concatenated samples."""
    count = 0
    total = 0.0
    buckets: Dict[str, float] = {}
    for h in hists:
        count += int(h.get("count", 0))
        total += float(h.get("sum", 0.0))
        for le, cum in h.get("buckets", {}).items():
            buckets[le] = buckets.get(le, 0) + cum
    def edge(le: str) -> float:
        return float("inf") if le == "inf" else float(le)
    return {"count": count, "sum": total,
            "buckets": dict(sorted(buckets.items(),
                                   key=lambda kv: edge(kv[0])))}


def merge_snapshots(snaps: Iterable[dict]) -> dict:
    """Fold registry snapshots into one fleet-level snapshot (see module
    docstring for the per-kind merge semantics)."""
    merged: Dict[str, Any] = {}
    hists: Dict[str, List[dict]] = {}
    for snap in snaps:
        for name, value in snap.items():
            if isinstance(value, dict):
                hists.setdefault(name, []).append(value)
                continue
            kind = metric_kind(name, value)
            if name not in merged:
                merged[name] = float(value)
            elif kind == "gauge" and name in GAUGE_MAX:
                merged[name] = max(merged[name], float(value))
            else:
                merged[name] += float(value)
    for name, hs in hists.items():
        merged[name] = merge_histogram_summaries(hs)
    return dict(sorted(merged.items()))


def _prom_label(label: Any) -> str:
    s = str(label)
    return s.replace("\\", "\\\\").replace('"', '\\"').replace("\n", " ")


def fleet_prometheus_text(snapshots: "Dict[Any, dict]",
                          merged: bool = True) -> str:
    """Replica-labeled Prometheus exposition over per-source snapshots.

    One ``# TYPE`` line per metric FAMILY (the exposition-format rule a
    naive per-replica concatenation breaks), then one labeled series per
    replica and — with ``merged=True`` — the unlabeled fleet-merged
    series."""
    from neuronx_distributed_tpu.obs.registry import _prom_name, _prom_val

    import math

    names: Dict[str, Any] = {}
    for snap in snapshots.values():
        for name, value in snap.items():
            names.setdefault(name, value)
    merged_snap = merge_snapshots(snapshots.values()) if merged else {}
    lines: List[str] = []
    for name in sorted(names):
        kind = metric_kind(name, names[name])
        pname = _prom_name(name)
        lines.append(f"# TYPE {pname} {kind}")
        series: List[Tuple[str, Any]] = [
            (f'replica="{_prom_label(label)}"', snap[name])
            for label, snap in sorted(snapshots.items(), key=lambda kv:
                                      str(kv[0]))
            if name in snap]
        if merged and name in merged_snap:
            series.append(("", merged_snap[name]))
        for label, value in series:
            if kind == "histogram":
                for le, cum in value.get("buckets", {}).items():
                    edge = "+Inf" if le == "inf" else le
                    sep = "," if label else ""
                    lines.append(
                        f'{pname}_bucket{{{label}{sep}le="{edge}"}} '
                        f"{_prom_val(float(cum))}")
                suffix = f"{{{label}}}" if label else ""
                lines.append(f"{pname}_sum{suffix} "
                             f"{_prom_val(float(value.get('sum', 0.0)))}")
                lines.append(f"{pname}_count{suffix} "
                             f"{_prom_val(float(value.get('count', 0)))}")
            else:
                v = float(value)
                if not math.isfinite(v):
                    continue
                suffix = f"{{{label}}}" if label else ""
                lines.append(f"{pname}{suffix} {_prom_val(v)}")
    return "\n".join(lines) + ("\n" if lines else "")


class FleetAggregator:
    """Live label -> registry sources with merge + exposition.

    ``sources`` is a dict of label -> registry (anything with
    ``snapshot()``), or a zero-arg callable returning one — the callable
    form follows a fleet through restarts (a rebuilt engine brings a fresh
    registry)."""

    def __init__(self, sources: "Dict[Any, Any] | Callable[[], Dict[Any, Any]]"):
        self._sources = sources

    @staticmethod
    def for_router(router: Any) -> "FleetAggregator":
        """Aggregate a :class:`~..serving.fleet.router.FleetRouter`: the
        router's own registry plus every LIVE replica engine's."""
        def sources() -> Dict[Any, Any]:
            out: Dict[Any, Any] = {"router": router.registry}
            for rid, replica in router.replicas.items():
                reg = (getattr(replica.engine, "registry", None)
                       if replica.alive else None)
                if reg is not None:
                    out[rid] = reg
            return out
        return FleetAggregator(sources)

    def snapshots(self) -> Dict[Any, dict]:
        sources = (self._sources() if callable(self._sources)
                   else self._sources)
        out: Dict[Any, dict] = {}
        for label, src in sources.items():
            out[label] = src.snapshot() if hasattr(src, "snapshot") \
                else dict(src)
        return out

    def merged(self) -> dict:
        return merge_snapshots(self.snapshots().values())

    def prometheus_text(self) -> str:
        """The ``/metrics?scope=fleet`` body."""
        return fleet_prometheus_text(self.snapshots())


class FleetHealth:
    """The fleet's control room: per-replica monitors + one fleet monitor,
    all streaming alert edges to ONE ``alerts.jsonl``.

    Wire it as ``FleetRouter(health=...)``: the router calls :meth:`step`
    every fleet iteration (cadenced by ``eval_every``), feeds terminal
    outputs through :meth:`note_output` (the fleet burn-rate rules'
    event stream), and raises/clears the ``replica_down`` condition on
    failover/warm restart.  Replica monitors are created lazily per live
    replica (scoped ``replica=`` tags on their rows) and dropped when the
    replica dies — a rebuilt engine gets a fresh monitor over its fresh
    registry."""

    def __init__(self, *, path: Optional[str] = None,
                 sink: Optional[AlertSink] = None,
                 rules: Optional[Sequence[Any]] = None,
                 replica_rules: "Optional[Callable[[], list]]" = None,
                 eval_every: int = 4,
                 clock: Callable[[], float] = time.monotonic,
                 wall: Callable[[], float] = time.time,
                 tracer: Any = None, registry: Any = None):
        if path is not None and sink is not None:
            raise ValueError("pass path= or sink=, not both")
        self.sink = sink if sink is not None else (
            AlertSink(path) if path is not None else None)
        self._own_sink = sink is None and path is not None
        self._clock = clock
        self._wall = wall
        self._tracer = tracer
        self.eval_every = int(eval_every)
        self._tick = 0
        self.fleet = HealthMonitor(
            rules if rules is not None else default_rules("fleet"),
            registry=registry, sink=self.sink, clock=clock, wall=wall,
            tracer=tracer, replica=-1)
        self._replica_rules = (replica_rules if replica_rules is not None
                               else (lambda: default_rules("serving")))
        self.replica_monitors: Dict[int, HealthMonitor] = {}
        # edge history of monitors whose replica died (the monitor object
        # goes with the engine, its emitted evidence must not): keeps
        # page_edges()/edges() consistent with the shared alerts.jsonl
        self._retired_edges: List[dict] = []

    def attach_router(self, router: Any) -> None:
        """Late-bind the fleet monitor's registry to the router's (the
        ``obs/alerts_*`` metrics then ride ``router_stats``' registry)."""
        self.fleet.attach_registry(router.registry)

    # -- router hooks ------------------------------------------------------

    def note_output(self, out: Any, now: Optional[float] = None) -> None:
        self.fleet.note_output(out, now)

    def replica_down(self, replica_id: int, cause: str = "",
                     now: Optional[float] = None) -> None:
        """A replica crashed out of rotation: fire ``replica_down`` (page)
        keyed by replica id; its per-replica monitor dies with the
        engine (a rebuilt engine gets a fresh one) but its emitted edges
        are retained."""
        dead = self.replica_monitors.pop(replica_id, None)
        if dead is not None:
            self._retired_edges.extend(dead.edges)
        self.fleet.set_condition(
            "replica_down", True, key=str(replica_id), severity="page",
            now=now, replica_id=replica_id, cause=cause)

    def replica_up(self, replica_id: int,
                   now: Optional[float] = None) -> None:
        """A warm restart re-entered rotation: resolve ``replica_down``."""
        self.fleet.set_condition(
            "replica_down", False, key=str(replica_id), severity="page",
            now=now, replica_id=replica_id)

    def replica_retired(self, replica_id: int, cause: str = "",
                        now: Optional[float] = None, *,
                        severity: str = "page") -> None:
        """A replica left rotation PERMANENTLY — crash budget spent, or a
        deliberate scale-in drain (pass ``severity="warn"``: nothing
        crashed, nobody should be paged).  Resolves the stale
        ``replica_down`` (the restart the pager was waiting on will never
        come) and fires the terminal ``replica_retired`` edge in its
        place, so autopilot and the pager can tell "warm restart coming"
        from "needs replacement".  The condition stays firing until
        :meth:`replica_replaced` reports a replacement joined."""
        dead = self.replica_monitors.pop(replica_id, None)
        if dead is not None:
            self._retired_edges.extend(dead.edges)
        self.fleet.set_condition(
            "replica_down", False, key=str(replica_id), severity="page",
            now=now, replica_id=replica_id)
        self.fleet.set_condition(
            "replica_retired", True, key=str(replica_id),
            severity=severity, now=now, replica_id=replica_id, cause=cause)

    def replica_replaced(self, replica_id: int, by: int,
                         now: Optional[float] = None) -> None:
        """Autoscale replaced a retired replica: resolve its terminal
        ``replica_retired`` (and any stale ``replica_down``) with the
        replacement's id on the edge."""
        self.fleet.set_condition(
            "replica_down", False, key=str(replica_id), severity="page",
            now=now, replica_id=replica_id, replaced_by=by)
        self.fleet.set_condition(
            "replica_retired", False, key=str(replica_id), severity="page",
            now=now, replica_id=replica_id, replaced_by=by)

    def step(self, router: Any, now: Optional[float] = None) -> None:
        """One fleet-iteration tick: every ``eval_every``-th call
        evaluates each live replica's monitor over its engine snapshot,
        then the fleet monitor over the MERGED snapshot (router registry +
        every live engine)."""
        self._tick += 1
        if self._tick % self.eval_every:
            return
        now = self._clock() if now is None else now
        snaps: List[dict] = [router.registry.snapshot()]
        for rid, replica in router.replicas.items():
            if not replica.alive:
                continue
            reg = getattr(replica.engine, "registry", None)
            if reg is None:
                continue
            snap = reg.snapshot()
            snaps.append(snap)
            mon = self.replica_monitors.get(rid)
            if mon is None:
                mon = self.replica_monitors[rid] = HealthMonitor(
                    self._replica_rules(), sink=self.sink,
                    clock=self._clock, wall=self._wall,
                    tracer=self._tracer, replica=rid)
            mon.evaluate(now, snapshot=snap)
        self.fleet.evaluate(now, snapshot=merge_snapshots(snaps))

    # -- views -------------------------------------------------------------

    def firing(self) -> List[dict]:
        out = list(self.fleet.firing())
        for rid, mon in self.replica_monitors.items():
            for a in mon.firing():
                out.append({**a, "replica": rid})
        return out

    def healthz(self) -> dict:
        return healthz_doc(self.firing())

    def edges(self) -> List[dict]:
        """Every alert edge this control room emitted — fleet monitor,
        live replica monitors, AND retired (crashed) replicas' monitors —
        matching the shared ``alerts.jsonl`` record for record (up to the
        per-monitor ring bounds)."""
        out = list(self.fleet.edges)
        for mon in self.replica_monitors.values():
            out.extend(mon.edges)
        out.extend(self._retired_edges)
        out.sort(key=lambda r: r.get("mono", 0.0))
        return out

    def page_edges(self) -> int:
        return sum(1 for r in self.edges()
                   if r["state"] == "firing" and r["severity"] == "page")

    def close(self) -> None:
        if self.sink is not None and self._own_sink:
            self.sink.close()


# -- offline merges (obs_report --run-dir fleet layouts) ---------------------

def _latest_by_tag(records: Iterable[dict]) -> Dict[str, dict]:
    latest: Dict[str, dict] = {}
    for r in records:
        tag = r.get("tag")
        if tag is None:
            continue
        prev = latest.get(tag)
        if prev is None or int(r.get("step", 0)) >= int(prev.get("step", 0)):
            latest[tag] = r
    return latest


def merge_scalar_records(streams: Sequence[List[dict]]) -> List[dict]:
    """Fold per-replica ``scalars.jsonl`` streams into ONE synthetic
    stream: each replica contributes its LATEST record per tag, and the
    per-tag values merge per kind — counters, histogram-flattened tags
    (``/le_*``, ``/count``, ``/sum`` — cumulative counts add) and gauges
    SUM; :data:`GAUGE_MAX` gauges take the max.  The result feeds the
    standard report machinery (``read_histograms`` reassembles the merged
    buckets exactly), where naively concatenating the raw streams would
    let one replica's snapshot shadow the others (latest step wins per
    tag)."""
    per_stream = [_latest_by_tag(s) for s in streams]
    tags: Dict[str, None] = {}
    for latest in per_stream:
        for tag in latest:
            tags.setdefault(tag)
    # histogram-flattened families: any tag with an /le_ edge marks its
    # base name, whose /count and /sum siblings must SUM like the edges do
    hist_bases = {tag.split("/le_")[0] for tag in tags if "/le_" in tag}
    out: List[dict] = []
    for tag in tags:
        recs = [latest[tag] for latest in per_stream if tag in latest]
        is_hist_part = "/le_" in tag or any(
            tag == f"{base}/{suffix}" for base in hist_bases
            for suffix in ("count", "sum"))
        values = [float(r["value"]) for r in recs]
        if (not is_hist_part
                and metric_kind(tag, recs[0].get("value")) == "gauge"
                and tag in GAUGE_MAX):
            value = max(values)
        else:
            value = sum(values)
        out.append({
            "step": max(int(r.get("step", 0)) for r in recs),
            "tag": tag,
            "value": value,
            "time": max(float(r.get("time", 0.0)) for r in recs),
        })
    return out


def merge_serving_stats(paths: Sequence[str]) -> List[dict]:
    """Concatenate per-replica ``serving_stats.jsonl`` streams (v4-
    tolerant), sorted by wall ``time`` so the merged stream reads like one
    engine's."""
    from neuronx_distributed_tpu.obs.report import read_serving_stats

    out: List[dict] = []
    for p in paths:
        if os.path.exists(p):
            out.extend(read_serving_stats(p))
    out.sort(key=lambda r: r.get("time", 0.0))
    return out


def merge_perf_files(paths: Sequence[str]) -> List[dict]:
    """Fold per-replica ``perf_attribution.jsonl`` files into one fleet
    attribution stream: per-family calls / device time / flops / bytes SUM
    across replicas (the fleet spent that much device time on prefill,
    full stop) and the derived roofline numbers are recomputed against the
    merged totals via :func:`~.perf.merge_perf_records`.  A single file
    passes through untouched."""
    from neuronx_distributed_tpu.obs.perf import (
        merge_perf_records,
        read_perf_attribution,
    )

    streams = [read_perf_attribution(p) for p in paths if os.path.exists(p)]
    streams = [s for s in streams if s]
    if not streams:
        return []
    if len(streams) == 1:
        return streams[0]
    return merge_perf_records(streams)


def discover_replica_dirs(run_dir: str) -> List[Tuple[str, str]]:
    """Fleet-layout discovery for ``obs_report --run-dir``: immediate
    subdirectories holding a ``scalars.jsonl`` or ``serving_stats.jsonl``
    are per-replica artifact dirs; returns ``[(label, dir), ...]`` sorted
    by label."""
    out: List[Tuple[str, str]] = []
    for sub in sorted(glob.glob(os.path.join(run_dir, "*"))):
        if not os.path.isdir(sub):
            continue
        if (os.path.exists(os.path.join(sub, "scalars.jsonl"))
                or os.path.exists(os.path.join(sub, "serving_stats.jsonl"))):
            out.append((os.path.basename(sub.rstrip(os.sep)), sub))
    return out


def summarize_router_stats(path: str) -> Optional[dict]:
    """Rollup of a fleet run's ``router_stats.jsonl`` for the report: how
    many terminal requests, their state mix, how many survived a failover
    (requeues > 0), and the replicas that served them."""
    if not os.path.exists(path):
        return None
    by_state: Dict[str, int] = {}
    requeued = 0
    migrated = 0
    migrations = 0
    roles: Dict[str, int] = {}
    replica_roles: Dict[int, str] = {}
    replicas: set = set()
    n = 0
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            rec = json.loads(line)
            n += 1
            by_state[rec.get("state", "?")] = \
                by_state.get(rec.get("state", "?"), 0) + 1
            if rec.get("requeues", 0) > 0:
                requeued += 1
            # v2 disagg evidence (absent in v1 records: zeros/empty)
            if rec.get("migrations", 0) > 0:
                migrated += 1
                migrations += int(rec["migrations"])
            role = rec.get("role")
            if role is not None:
                roles[role] = roles.get(role, 0) + 1
                if rec.get("replica", -1) >= 0:
                    replica_roles[rec["replica"]] = role
            if rec.get("replica", -1) >= 0:
                replicas.add(rec["replica"])
    if not n:
        return None
    return {
        "records": n,
        "by_state": dict(sorted(by_state.items())),
        "requeued": requeued,
        "replicas_seen": sorted(replicas),
        # disagg rollup: requests that took >=1 KV-migration hop, total
        # hops, terminal-role mix, and the per-replica role map (empty on
        # v1 streams and plain fleets)
        "migrated": migrated,
        "migrations": migrations,
        "roles": dict(sorted(roles.items())),
        "replica_roles": {str(k): v
                          for k, v in sorted(replica_roles.items())},
    }
