// nxd_data: memory-mapped token-dataset reader with background prefetch.
//
// Native data path for the TPU framework — the role torch's
// MpDeviceLoader + DistributedSampler + HDF5 readers play in the reference
// (tp_zero1_llama2_7b_hf_pretrain.py:192-198; examples' create_pretraining_dataset).
// One flat token file is chunked into fixed (seq_len+1)-token samples, the
// chunk order is shuffled per epoch with a seed-deterministic Fisher-Yates
// (splitmix64 — mirrored bit-for-bit by the Python fallback), chunks are
// round-robin partitioned across DP ranks, and a small thread pool copies
// upcoming batches into a ring of pinned host buffers so the train loop
// never blocks on page faults.
//
// File format ("NXDT"): magic u32 'NXDT' LE, u32 version=1,
// u32 dtype (2=int32, 1=uint16), u64 num_tokens, then the tokens.

#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <mutex>
#include <thread>
#include <vector>

#include <fcntl.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

namespace {

constexpr uint32_t kMagic = 0x5444584e;  // "NXDT" little-endian
constexpr uint32_t kVersion = 1;
constexpr uint32_t kDtypeU16 = 1;
constexpr uint32_t kDtypeI32 = 2;

struct Header {
  uint32_t magic;
  uint32_t version;
  uint32_t dtype;
  uint32_t reserved;
  uint64_t num_tokens;
};

// splitmix64: tiny, seedable, and trivially reproducible from Python.
inline uint64_t splitmix64(uint64_t& state) {
  uint64_t z = (state += 0x9e3779b97f4a7c15ULL);
  z = (z ^ (z >> 30)) * 0xbf58476d1ce4e5b9ULL;
  z = (z ^ (z >> 27)) * 0x94d049bb133111ebULL;
  return z ^ (z >> 31);
}

}  // namespace

extern "C" {

struct NxdDataset {
  int fd = -1;
  const uint8_t* base = nullptr;
  size_t map_len = 0;
  uint32_t dtype = 0;
  uint64_t num_tokens = 0;
  const uint8_t* tokens = nullptr;
};

NxdDataset* nxd_open(const char* path) {
  int fd = ::open(path, O_RDONLY);
  if (fd < 0) return nullptr;
  struct stat st;
  if (fstat(fd, &st) != 0 || (size_t)st.st_size < sizeof(Header)) {
    ::close(fd);
    return nullptr;
  }
  void* mem = mmap(nullptr, st.st_size, PROT_READ, MAP_PRIVATE, fd, 0);
  if (mem == MAP_FAILED) {
    ::close(fd);
    return nullptr;
  }
  auto* h = reinterpret_cast<const Header*>(mem);
  if (h->magic != kMagic || h->version != kVersion ||
      (h->dtype != kDtypeU16 && h->dtype != kDtypeI32)) {
    munmap(mem, st.st_size);
    ::close(fd);
    return nullptr;
  }
  size_t tok_bytes = h->num_tokens * (h->dtype == kDtypeU16 ? 2 : 4);
  if (sizeof(Header) + tok_bytes > (size_t)st.st_size) {
    munmap(mem, st.st_size);
    ::close(fd);
    return nullptr;
  }
  auto* ds = new NxdDataset();
  ds->fd = fd;
  ds->base = reinterpret_cast<const uint8_t*>(mem);
  ds->map_len = st.st_size;
  ds->dtype = h->dtype;
  ds->num_tokens = h->num_tokens;
  ds->tokens = ds->base + sizeof(Header);
  return ds;
}

void nxd_close(NxdDataset* ds) {
  if (!ds) return;
  if (ds->base) munmap(const_cast<uint8_t*>(ds->base), ds->map_len);
  if (ds->fd >= 0) ::close(ds->fd);
  delete ds;
}

uint64_t nxd_num_tokens(NxdDataset* ds) { return ds ? ds->num_tokens : 0; }

uint64_t nxd_num_chunks(NxdDataset* ds, uint32_t seq_len) {
  if (!ds || seq_len == 0) return 0;
  // each chunk needs seq_len+1 tokens (input + shifted label); chunks are
  // laid out back-to-back on a seq_len stride so every token is a label once
  if (ds->num_tokens < (uint64_t)seq_len + 1) return 0;
  return (ds->num_tokens - 1) / seq_len;
}

struct Slot {
  std::vector<int32_t> buf;
  int64_t batch_id = -1;  // which global batch fills this slot
  bool ready = false;
};

struct NxdLoader {
  NxdDataset* ds = nullptr;
  uint32_t batch = 0, seq_len = 0, dp_rank = 0, dp_size = 1;
  uint64_t seed = 0, epoch = 0;
  uint32_t num_threads = 1;
  std::vector<uint64_t> order;     // shuffled chunk ids for THIS rank
  uint64_t num_batches = 0;        // per epoch for this rank
  // prefetch machinery
  std::vector<Slot> slots;
  std::vector<std::thread> workers;
  std::mutex mu;
  std::condition_variable cv_ready;   // consumer waits on
  std::condition_variable cv_free;    // producers wait on
  std::atomic<int64_t> next_fill{0};  // next batch id to be claimed by a worker
  int64_t next_consume = 0;           // next batch id the consumer expects
  bool shutdown = false;

  size_t sample_tokens() const { return (size_t)seq_len + 1; }
  size_t batch_tokens() const { return (size_t)batch * sample_tokens(); }
};

namespace {

void build_order(NxdLoader* L) {
  uint64_t total = nxd_num_chunks(L->ds, L->seq_len);
  std::vector<uint64_t> all(total);
  for (uint64_t i = 0; i < total; ++i) all[i] = i;
  // Fisher-Yates with splitmix64 — mirrored in the Python fallback
  uint64_t state = L->seed + 0x51ed2700 * (L->epoch + 1);
  for (uint64_t i = total; i > 1; --i) {
    uint64_t j = splitmix64(state) % i;
    std::swap(all[i - 1], all[j]);
  }
  // round-robin DP partition, truncated to a globally uniform batch count:
  // every rank must yield the same number of batches or the longer ranks
  // block forever in the first collective after a short rank's loader is
  // exhausted (the reference's DistributedSampler pads/truncates likewise)
  L->order.clear();
  for (uint64_t i = L->dp_rank; i < total; i += L->dp_size)
    L->order.push_back(all[i]);
  uint64_t per_rank = total / L->dp_size;  // min share across ranks
  L->num_batches = per_rank / L->batch;
  L->order.resize(L->num_batches * L->batch);
}

void copy_chunk(NxdLoader* L, uint64_t chunk, int32_t* out) {
  const size_t n = L->sample_tokens();
  const uint64_t start = chunk * (uint64_t)L->seq_len;
  if (L->ds->dtype == kDtypeI32) {
    std::memcpy(out, L->ds->tokens + start * 4, n * 4);
  } else {
    auto* src = reinterpret_cast<const uint16_t*>(L->ds->tokens) + start;
    for (size_t i = 0; i < n; ++i) out[i] = src[i];
  }
}

void fill_batch(NxdLoader* L, int64_t batch_id, int32_t* out) {
  for (uint32_t s = 0; s < L->batch; ++s) {
    uint64_t chunk = L->order[(uint64_t)batch_id * L->batch + s];
    copy_chunk(L, chunk, out + (size_t)s * L->sample_tokens());
  }
}

void worker_loop(NxdLoader* L) {
  for (;;) {
    int64_t id = L->next_fill.fetch_add(1);
    if (id >= (int64_t)L->num_batches) return;
    Slot& slot = L->slots[id % L->slots.size()];
    {
      std::unique_lock<std::mutex> lk(L->mu);
      // wait until the consumer has drained the slot's previous occupant
      L->cv_free.wait(lk, [&] {
        return L->shutdown || (!slot.ready && L->next_consume > id - (int64_t)L->slots.size());
      });
      if (L->shutdown) return;
    }
    fill_batch(L, id, slot.buf.data());
    {
      std::lock_guard<std::mutex> lk(L->mu);
      slot.batch_id = id;
      slot.ready = true;
    }
    L->cv_ready.notify_all();
  }
}

void start_workers(NxdLoader* L, uint32_t num_threads) {
  for (uint32_t i = 0; i < num_threads; ++i)
    L->workers.emplace_back(worker_loop, L);
}

void stop_workers(NxdLoader* L) {
  {
    std::lock_guard<std::mutex> lk(L->mu);
    L->shutdown = true;
  }
  L->cv_free.notify_all();
  for (auto& t : L->workers) t.join();
  L->workers.clear();
  L->shutdown = false;
}

}  // namespace

NxdLoader* nxd_loader_create(NxdDataset* ds, uint32_t batch, uint32_t seq_len,
                             uint32_t dp_rank, uint32_t dp_size, uint64_t seed,
                             uint32_t prefetch_depth, uint32_t num_threads) {
  if (!ds || batch == 0 || seq_len == 0 || dp_size == 0 || dp_rank >= dp_size)
    return nullptr;
  auto* L = new NxdLoader();
  L->ds = ds;
  L->batch = batch;
  L->seq_len = seq_len;
  L->dp_rank = dp_rank;
  L->dp_size = dp_size;
  L->seed = seed;
  build_order(L);
  if (prefetch_depth == 0) prefetch_depth = 2;
  if (num_threads == 0) num_threads = 1;
  L->num_threads = num_threads;
  L->slots.resize(prefetch_depth);
  for (auto& s : L->slots) s.buf.resize(L->batch_tokens());
  start_workers(L, num_threads);
  return L;
}

void nxd_loader_destroy(NxdLoader* L) {
  if (!L) return;
  stop_workers(L);
  delete L;
}

uint64_t nxd_loader_num_batches(NxdLoader* L) { return L ? L->num_batches : 0; }

// Reshuffle for a new epoch and restart the prefetchers, optionally skipping
// the first `skip_batches` (checkpoint-resume semantics: the reference skips
// already-consumed batches, run_llama_nxd.py:233-244).
void nxd_loader_set_epoch(NxdLoader* L, uint64_t epoch, uint64_t skip_batches) {
  if (!L) return;
  stop_workers(L);
  L->epoch = epoch;
  build_order(L);
  for (auto& s : L->slots) {
    s.ready = false;
    s.batch_id = -1;
  }
  L->next_fill.store((int64_t)skip_batches);
  L->next_consume = (int64_t)skip_batches;
  start_workers(L, L->num_threads);
}

// Blocking: fills out[batch*(seq_len+1)] with the next batch; returns the
// batch index within the epoch, or -1 when the epoch is exhausted.
int64_t nxd_loader_next(NxdLoader* L, int32_t* out) {
  if (!L) return -1;
  if (L->next_consume >= (int64_t)L->num_batches) return -1;
  const int64_t want = L->next_consume;
  Slot& slot = L->slots[want % L->slots.size()];
  {
    std::unique_lock<std::mutex> lk(L->mu);
    L->cv_ready.wait(lk, [&] { return slot.ready && slot.batch_id == want; });
    std::memcpy(out, slot.buf.data(), slot.buf.size() * sizeof(int32_t));
    slot.ready = false;
    slot.batch_id = -1;
    L->next_consume = want + 1;
  }
  L->cv_free.notify_all();
  return want;
}


// First-fit row assignment for sequence packing — the placement loop of
// data/packing.pack_documents, bit-identical to its Python form: each piece
// (length <= seq_len) goes into the first row with room among the last
// `window` opened rows, else opens a new row.  lengths[n] -> out_rows[n]
// (row index per piece); returns the number of rows, or -1 on a bad length.
// Pure integer bookkeeping, but Python-loop-bound at corpus scale (millions
// of documents): this native form removes the interpreter from the only
// O(pieces * window) part while the numpy row assembly stays in Python.
int64_t nxd_pack_assign(const int32_t* lengths, int64_t n, int32_t seq_len,
                        int32_t window, int32_t* out_rows) {
  if (!lengths || !out_rows || seq_len <= 0 || window < 0) return -1;
  std::vector<int32_t> space;
  space.reserve(4096);
  for (int64_t i = 0; i < n; ++i) {
    const int32_t need = lengths[i];
    if (need < 0 || need > seq_len) return -1;
    bool placed = false;
    const int64_t sz = (int64_t)space.size();
    const int64_t lo = sz > window ? sz - window : 0;
    for (int64_t r = lo; r < sz; ++r) {
      if (space[r] >= need) {
        out_rows[i] = (int32_t)r;
        space[r] -= need;
        placed = true;
        break;
      }
    }
    if (!placed) {
      out_rows[i] = (int32_t)space.size();
      space.push_back(seq_len - need);
    }
  }
  return (int64_t)space.size();
}

}  // extern "C"
