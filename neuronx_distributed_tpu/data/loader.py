"""Token-dataset loader: ctypes bindings over the native ``nxd_data`` C++
library, with a bit-identical pure-numpy fallback.

This is the framework's data pipeline (the role of MpDeviceLoader +
DistributedSampler + the HDF5 readers in the reference's training harnesses,
``tp_zero1_llama2_7b_hf_pretrain.py:192-216``): a flat tokenized corpus is
chunked into ``seq_len+1``-token samples, shuffled per epoch with a
seed-deterministic Fisher-Yates (splitmix64, identical in C++ and Python),
round-robin sharded across DP ranks, and prefetched on background threads
(native path).  ``ids``/``labels`` come out already shifted.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import weakref
from typing import Iterator, Optional, Tuple

import numpy as np

from neuronx_distributed_tpu.resilience.faults import fault_point
from neuronx_distributed_tpu.utils.logger import get_logger

logger = get_logger(__name__)

_MAGIC = 0x5444584E  # "NXDT"
_VERSION = 1
_DTYPES = {1: np.uint16, 2: np.int32}
_DTYPE_CODES = {np.dtype(np.uint16): 1, np.dtype(np.int32): 2}

_CSRC = os.path.join(os.path.dirname(__file__), "csrc", "loader.cpp")
_BUILD_DIR = os.path.join(os.path.dirname(__file__), "_build")
_LIB_PATH = os.path.join(_BUILD_DIR, "libnxd_data.so")

_lib = None
_lib_tried = False


def _build_native() -> Optional[str]:
    os.makedirs(_BUILD_DIR, exist_ok=True)
    # build to a per-pid temp name then rename atomically: N DP processes on
    # one host may race to build the same .so
    tmp = f"{_LIB_PATH}.{os.getpid()}.tmp"
    cmd = ["g++", "-O3", "-shared", "-fPIC", "-std=c++17", "-pthread",
           _CSRC, "-o", tmp]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        os.replace(tmp, _LIB_PATH)
        return _LIB_PATH
    except (subprocess.SubprocessError, FileNotFoundError, OSError) as e:
        logger.warning("native data loader build failed (%s); using numpy fallback", e)
        if os.path.exists(tmp):
            os.unlink(tmp)
        return None


def _load_native():
    """Compile (once) and load the native library; None if unavailable."""
    global _lib, _lib_tried
    if _lib_tried:
        return _lib
    _lib_tried = True
    path = _LIB_PATH
    if not os.path.exists(path) or os.path.getmtime(path) < os.path.getmtime(_CSRC):
        path = _build_native()
    if path is None:
        return None
    try:
        lib = ctypes.CDLL(path)
    except OSError as e:
        # e.g. a concurrently-built half-written .so; numpy fallback instead
        logger.warning("loading native data loader failed (%s); using numpy fallback", e)
        return None
    lib.nxd_open.restype = ctypes.c_void_p
    lib.nxd_open.argtypes = [ctypes.c_char_p]
    lib.nxd_close.argtypes = [ctypes.c_void_p]
    lib.nxd_num_tokens.restype = ctypes.c_uint64
    lib.nxd_num_tokens.argtypes = [ctypes.c_void_p]
    lib.nxd_num_chunks.restype = ctypes.c_uint64
    lib.nxd_num_chunks.argtypes = [ctypes.c_void_p, ctypes.c_uint32]
    lib.nxd_loader_create.restype = ctypes.c_void_p
    lib.nxd_loader_create.argtypes = [
        ctypes.c_void_p, ctypes.c_uint32, ctypes.c_uint32, ctypes.c_uint32,
        ctypes.c_uint32, ctypes.c_uint64, ctypes.c_uint32, ctypes.c_uint32]
    lib.nxd_loader_destroy.argtypes = [ctypes.c_void_p]
    lib.nxd_loader_num_batches.restype = ctypes.c_uint64
    lib.nxd_loader_num_batches.argtypes = [ctypes.c_void_p]
    lib.nxd_loader_set_epoch.argtypes = [ctypes.c_void_p, ctypes.c_uint64, ctypes.c_uint64]
    lib.nxd_loader_next.restype = ctypes.c_int64
    lib.nxd_loader_next.argtypes = [ctypes.c_void_p, ctypes.POINTER(ctypes.c_int32)]
    if hasattr(lib, "nxd_pack_assign"):  # absent only in a stale cached .so
        lib.nxd_pack_assign.restype = ctypes.c_int64
        lib.nxd_pack_assign.argtypes = [
            ctypes.POINTER(ctypes.c_int32), ctypes.c_int64,
            ctypes.c_int32, ctypes.c_int32, ctypes.POINTER(ctypes.c_int32)]
    _lib = lib
    return _lib


def native_pack_assign(lengths: np.ndarray, seq_len: int,
                       window: int) -> Optional[Tuple[np.ndarray, int]]:
    """First-fit row assignment via the native library (``nxd_pack_assign``
    in ``csrc/loader.cpp``); ``None`` ONLY when the native path is
    unavailable — callers fall back to the bit-identical Python loop
    (``data.packing._assign_rows_py``).  Invalid input (a piece longer than
    ``seq_len``, which no assignment can place) raises rather than being
    conflated with unavailability: the fallback must never silently run a
    workload the native path rejected."""
    lib = _load_native()
    if lib is None or not hasattr(lib, "nxd_pack_assign"):
        return None
    lengths = np.ascontiguousarray(lengths, np.int32)
    out = np.empty(len(lengths), np.int32)
    n_rows = lib.nxd_pack_assign(
        lengths.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        ctypes.c_int64(len(lengths)), ctypes.c_int32(int(seq_len)),
        ctypes.c_int32(int(window)),
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)))
    if n_rows < 0:
        raise ValueError(
            f"pack_assign: invalid input (seq_len={seq_len}, window={window}, "
            f"max piece length {int(lengths.max()) if len(lengths) else 0}) — "
            "every piece must satisfy 0 <= length <= seq_len"
        )
    return out, int(n_rows)


# ---------------------------------------------------------------------------
# file format
# ---------------------------------------------------------------------------


def write_token_file(path: str, tokens: np.ndarray) -> None:
    """Write a flat token array as an NXDT file (uint16 when the vocab fits,
    int32 otherwise)."""
    tokens = np.ascontiguousarray(tokens).reshape(-1)
    if tokens.size and tokens.min() < 0:
        raise ValueError("token ids must be non-negative (found negative values)")
    if tokens.dtype not in (np.uint16, np.int32):
        tokens = tokens.astype(np.int32 if tokens.max(initial=0) > 65535 else np.uint16)
    code = _DTYPE_CODES[tokens.dtype]
    head32 = np.array([_MAGIC, _VERSION, code, 0], np.uint32)
    with open(path, "wb") as f:
        f.write(head32.tobytes())
        f.write(np.uint64(tokens.size).tobytes())
        f.write(tokens.tobytes())


def read_token_file(path: str) -> np.ndarray:
    """Read an NXDT file back into a flat numpy array (host-side utility)."""
    with open(path, "rb") as f:
        head32 = np.frombuffer(f.read(16), np.uint32)
        if head32[0] != _MAGIC or head32[1] != _VERSION:
            raise ValueError(f"{path} is not an NXDT token file")
        n = int(np.frombuffer(f.read(8), np.uint64)[0])
        return np.frombuffer(f.read(), _DTYPES[int(head32[2])], count=n)


# ---------------------------------------------------------------------------
# deterministic shuffle shared with C++
# ---------------------------------------------------------------------------

_M64 = (1 << 64) - 1


def _splitmix64(state: int) -> Tuple[int, int]:
    state = (state + 0x9E3779B97F4A7C15) & _M64
    z = state
    z = ((z ^ (z >> 30)) * 0xBF58476D1CE4E5B9) & _M64
    z = ((z ^ (z >> 27)) * 0x94D049BB133111EB) & _M64
    return z ^ (z >> 31), state


def _shuffled_chunks(total: int, seed: int, epoch: int) -> np.ndarray:
    """Fisher-Yates identical to the C++ ``build_order``."""
    order = np.arange(total, dtype=np.uint64)
    state = (seed + 0x51ED2700 * (epoch + 1)) & _M64
    for i in range(total, 1, -1):
        r, state = _splitmix64(state)
        j = r % i
        order[i - 1], order[j] = order[j], order[i - 1]
    return order


class TokenDataset:
    """Handle over an NXDT token file (native mmap when available)."""

    def __init__(self, path: str):
        self.path = path
        self._lib = _load_native()
        self._handle = None
        self._np_tokens = None
        self._loaders: "weakref.WeakSet" = weakref.WeakSet()
        if self._lib is not None:
            self._handle = self._lib.nxd_open(path.encode())
            if not self._handle:
                raise ValueError(f"failed to open token file {path}")
            self.num_tokens = int(self._lib.nxd_num_tokens(self._handle))
        else:
            self._np_tokens = read_token_file(path)
            self.num_tokens = int(self._np_tokens.size)

    @property
    def is_native(self) -> bool:
        return self._handle is not None

    def num_chunks(self, seq_len: int) -> int:
        if self.num_tokens < seq_len + 1:
            return 0
        return (self.num_tokens - 1) // seq_len

    def max_token_id(self) -> int:
        """Largest token id in the file (one streaming mmap scan, cached —
        never a resident copy of the corpus, whichever loader path is
        active)."""
        if not hasattr(self, "_max_token"):
            if self._np_tokens is not None:
                data = self._np_tokens
            else:
                with open(self.path, "rb") as f:
                    head32 = np.frombuffer(f.read(16), np.uint32)
                    if head32[0] != _MAGIC or head32[1] != _VERSION:
                        raise ValueError(f"{self.path} is not an NXDT token file")
                    n = int(np.frombuffer(f.read(8), np.uint64)[0])
                data = np.memmap(self.path, _DTYPES[int(head32[2])], mode="r",
                                 offset=24, shape=(n,))
            self._max_token = int(data.max()) if data.size else 0
        return self._max_token

    def validate_vocab(self, vocab_size: int, what: str = "model") -> None:
        """Fail loudly when the file holds ids outside ``[0, vocab_size)`` —
        an out-of-range id otherwise trains to a silent NaN loss (the
        vocab-parallel CE's psum-MAX eats the bad one-hot).  One shared
        check for every launcher."""
        if self.max_token_id() >= vocab_size:
            raise ValueError(
                f"data file {self.path} contains token id {self.max_token_id()} "
                f">= {what} vocab_size {vocab_size}; rebuild the data or pick "
                "a larger-vocab config (out-of-range ids train to NaN)"
            )

    def close(self):
        if self._handle is not None:
            # destroy live loaders FIRST: their prefetch threads read the
            # dataset's mmap, so nxd_close before nxd_loader_destroy is a
            # use-after-free (segfaulted under GC ordering in the wild)
            for loader in list(self._loaders):
                loader.close()
            self._lib.nxd_close(self._handle)
            self._handle = None

    def __del__(self):  # pragma: no cover - GC timing
        try:
            self.close()
        except Exception:
            pass


class TokenDataLoader:
    """Iterates ``{"ids": [B, S], "labels": [B, S]}`` int32 batches for one
    DP rank.  Deterministic across restarts: ``(seed, epoch)`` fixes the
    order, ``skip_batches`` resumes mid-epoch (the reference's
    consumed-batch skip, ``run_llama_nxd.py:233-244``)."""

    def __init__(
        self,
        dataset: TokenDataset,
        batch_size: int,
        seq_len: int,
        dp_rank: int = 0,
        dp_size: int = 1,
        seed: int = 0,
        prefetch_depth: int = 4,
        num_threads: int = 2,
    ):
        if dp_rank >= dp_size:
            raise ValueError(f"dp_rank {dp_rank} >= dp_size {dp_size}")
        self.ds = dataset
        self.batch_size = batch_size
        self.seq_len = seq_len
        self.dp_rank = dp_rank
        self.dp_size = dp_size
        self.seed = seed
        self.epoch = 0
        self._cursor = 0
        self._loader = None
        if dataset.is_native:
            lib = dataset._lib
            self._loader = lib.nxd_loader_create(
                dataset._handle, batch_size, seq_len, dp_rank, dp_size, seed,
                prefetch_depth, num_threads)
            if not self._loader:
                raise ValueError("native loader creation failed")
            dataset._loaders.add(self)  # dataset.close() tears us down first
            self.num_batches = int(lib.nxd_loader_num_batches(self._loader))
        else:
            # globally uniform count (min share across ranks) so every dp
            # rank yields the same number of batches — mirrors loader.cpp
            total = dataset.num_chunks(seq_len)
            self.num_batches = (total // dp_size) // batch_size

    def set_epoch(self, epoch: int, skip_batches: int = 0) -> None:
        """Reshuffle for ``epoch`` and reset the cursor; call before each
        epoch (both paths are single-shot between calls).  ``skip_batches``
        resumes mid-epoch."""
        self.epoch = epoch
        self._cursor = skip_batches
        if self._loader is not None:
            self.ds._lib.nxd_loader_set_epoch(self._loader, epoch, skip_batches)

    def _iter_native(self) -> Iterator[dict]:
        lib = self.ds._lib
        out = np.empty((self.batch_size, self.seq_len + 1), np.int32)
        ptr = out.ctypes.data_as(ctypes.POINTER(ctypes.c_int32))
        while True:
            fault_point("data/next_batch", epoch=self.epoch, rank=self.dp_rank)
            got = lib.nxd_loader_next(self._loader, ptr)
            if got < 0:
                return
            yield {"ids": out[:, :-1].copy(), "labels": out[:, 1:].copy()}

    def _iter_numpy(self) -> Iterator[dict]:
        # single-shot per set_epoch, matching the native path: once the epoch
        # is exhausted, further iteration yields nothing until set_epoch
        total = self.ds.num_chunks(self.seq_len)
        order = _shuffled_chunks(total, self.seed, self.epoch)
        mine = order[self.dp_rank::self.dp_size][: self.num_batches * self.batch_size]
        toks = self.ds._np_tokens
        n = self.seq_len
        while self._cursor < self.num_batches:
            fault_point("data/next_batch", epoch=self.epoch, rank=self.dp_rank)
            b = self._cursor
            self._cursor += 1
            chunk_ids = mine[b * self.batch_size:(b + 1) * self.batch_size]
            batch = np.stack(
                [toks[int(c) * n:int(c) * n + n + 1].astype(np.int32) for c in chunk_ids]
            )
            yield {"ids": batch[:, :-1], "labels": batch[:, 1:]}

    def __iter__(self) -> Iterator[dict]:
        if self._loader is not None:
            return self._iter_native()
        return self._iter_numpy()

    def __len__(self) -> int:
        return self.num_batches

    def close(self):
        if self._loader is not None:
            self.ds._lib.nxd_loader_destroy(self._loader)
            self._loader = None

    def __del__(self):  # pragma: no cover - GC timing
        try:
            self.close()
        except Exception:
            pass
