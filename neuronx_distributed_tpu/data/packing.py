"""Sequence packing: fill fixed-length training rows from ragged documents.

The reference's data prep concatenates tokenized documents and chunks them to
``seq_len`` (the ``get_examples`` preprocessing its example trainers assume);
this module provides that as a library function plus the loss/attention
metadata the trainer consumes:

- ``pack_documents`` — greedy first-fit packing of ragged docs into
  ``[N, seq_len]`` rows with an EOS separator, emitting ``labels`` (ignore
  index over padding and separators if requested) and ``segment_ids`` so an
  attention implementation can optionally block cross-document attention;
- ``concat_and_chunk`` — the reference's simpler concatenate-everything
  layout (documents flow across row boundaries, maximum token utilization).
"""

from __future__ import annotations

from typing import Iterable, List, Tuple

import numpy as np

IGNORE = -100


def concat_and_chunk(
    docs: Iterable[np.ndarray], seq_len: int, eos_id: int
) -> Tuple[np.ndarray, np.ndarray]:
    """Concatenate ``docs`` (1-D int arrays) with EOS separators and chunk
    into ``[N, seq_len]`` rows of ``ids`` and next-token ``labels``; the tail
    that does not fill a row is dropped (the reference's preprocessing
    convention)."""
    stream: List[np.ndarray] = []
    for d in docs:
        stream.append(np.asarray(d, np.int32).ravel())
        stream.append(np.asarray([eos_id], np.int32))
    if not stream:
        return np.zeros((0, seq_len), np.int32), np.zeros((0, seq_len), np.int32)
    flat = np.concatenate(stream)
    # need one extra token so every position has a next-token label
    n = (len(flat) - 1) // seq_len
    ids = flat[: n * seq_len].reshape(n, seq_len).astype(np.int32)
    labels = flat[1 : n * seq_len + 1].reshape(n, seq_len).astype(np.int32)
    return ids, labels


def pack_documents(
    docs: Iterable[np.ndarray],
    seq_len: int,
    eos_id: int,
    pad_id: int = 0,
    mask_separators: bool = False,
) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Greedy first-fit packing: each document (+1 EOS) is placed whole into
    the first row with room; rows never split a document.  Returns
    ``(ids, labels, segment_ids)`` each ``[N, seq_len]``:

    - ``labels`` are next-token within each document, ``IGNORE`` (-100) on
      padding, on the EOS position itself (nothing follows it), and
      (optionally, ``mask_separators``) on the position that predicts EOS;
    - ``segment_ids`` number pieces within a row from 1 (0 = padding), the
      mask an attention kernel needs to block cross-document attention.

    Documents longer than ``seq_len`` are split into ``seq_len``-sized pieces
    first.  Crucially the split inserts NO fake EOS: labels are computed over
    the whole document before splitting, so a piece's last position predicts
    the document's true next token — the model is never taught that documents
    end at arbitrary ``seq_len`` boundaries."""
    pieces: List[Tuple[np.ndarray, np.ndarray]] = []
    for d in docs:
        d = np.asarray(d, np.int32).ravel()
        toks = np.concatenate([d, np.asarray([eos_id], np.int32)])
        labs = np.concatenate([toks[1:], np.asarray([IGNORE], np.int32)])
        if mask_separators and len(toks) >= 2:
            labs[len(toks) - 2] = IGNORE  # the position predicting EOS
        for i in range(0, len(toks), seq_len):
            pieces.append((toks[i : i + seq_len], labs[i : i + seq_len]))

    # first-fit over a bounded lookback of recently-opened rows: full
    # first-fit is O(pieces x rows) (quadratic at corpus scale); a window
    # keeps packing near-identical at O(pieces x window).  The placement
    # loop is the interpreter-bound part at corpus scale, so it runs in the
    # native library when available (csrc/loader.cpp nxd_pack_assign), with
    # this Python loop as the bit-identical fallback.
    window = 64
    lengths = np.asarray([len(p[0]) for p in pieces], np.int32)
    from neuronx_distributed_tpu.data.loader import native_pack_assign

    assigned = native_pack_assign(lengths, seq_len, window)
    if assigned is None:
        assigned = _assign_rows_py(lengths, seq_len, window)
    row_of_piece, N = assigned

    ids = np.full((N, seq_len), pad_id, np.int32)
    labels = np.full((N, seq_len), IGNORE, np.int32)
    segs = np.zeros((N, seq_len), np.int32)
    pos = [0] * N
    nseg = [0] * N
    for (ptoks, plabs), r in zip(pieces, row_of_piece):
        L = len(ptoks)
        p = pos[r]
        nseg[r] += 1
        ids[r, p : p + L] = ptoks
        labels[r, p : p + L] = plabs
        segs[r, p : p + L] = nseg[r]
        pos[r] += L
    return ids, labels, segs


def _assign_rows_py(lengths: np.ndarray, seq_len: int,
                    window: int) -> Tuple[np.ndarray, int]:
    """Pure-Python window-bounded first-fit — the reference semantics the
    native ``nxd_pack_assign`` must match bit-for-bit."""
    space: List[int] = []
    out = np.empty(len(lengths), np.int32)
    for i, need in enumerate(lengths):
        placed = False
        lo = max(0, len(space) - window)
        for r in range(lo, len(space)):
            if space[r] >= need:
                out[i] = r
                space[r] -= need
                placed = True
                break
        if not placed:
            out[i] = len(space)
            space.append(seq_len - int(need))
    return out, len(space)


def segment_positions(segment_ids: np.ndarray) -> np.ndarray:
    """Per-document RoPE positions from ``[N, S]`` segment ids: position =
    offset within the segment's contiguous run (the trailing padding run
    restarts from 0 as well; its positions are inert — padding rows carry
    IGNORE labels and segment id 0 blocks their attention).  The companion
    of :func:`pack_documents` every packed consumer needs."""
    segment_ids = np.asarray(segment_ids)
    S = segment_ids.shape[-1]
    start = np.zeros_like(segment_ids)
    changes = segment_ids[..., 1:] != segment_ids[..., :-1]
    start[..., 1:] = np.where(changes, np.arange(1, S), 0)
    start = np.maximum.accumulate(start, axis=-1)
    return (np.arange(S) - start).astype(np.int32)
