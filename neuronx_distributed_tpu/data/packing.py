"""Sequence packing: fill fixed-length training rows from ragged documents.

The reference's data prep concatenates tokenized documents and chunks them to
``seq_len`` (the ``get_examples`` preprocessing its example trainers assume);
this module provides that as a library function plus the loss/attention
metadata the trainer consumes:

- ``pack_documents`` — greedy first-fit packing of ragged docs into
  ``[N, seq_len]`` rows with an EOS separator, emitting ``labels`` (ignore
  index over padding and separators if requested) and ``segment_ids`` so an
  attention implementation can optionally block cross-document attention;
- ``concat_and_chunk`` — the reference's simpler concatenate-everything
  layout (documents flow across row boundaries, maximum token utilization).
"""

from __future__ import annotations

from typing import Iterable, List, Tuple

import numpy as np

IGNORE = -100


def concat_and_chunk(
    docs: Iterable[np.ndarray], seq_len: int, eos_id: int
) -> Tuple[np.ndarray, np.ndarray]:
    """Concatenate ``docs`` (1-D int arrays) with EOS separators and chunk
    into ``[N, seq_len]`` rows of ``ids`` and next-token ``labels``; the tail
    that does not fill a row is dropped (the reference's preprocessing
    convention)."""
    stream: List[np.ndarray] = []
    for d in docs:
        stream.append(np.asarray(d, np.int32).ravel())
        stream.append(np.asarray([eos_id], np.int32))
    if not stream:
        return np.zeros((0, seq_len), np.int32), np.zeros((0, seq_len), np.int32)
    flat = np.concatenate(stream)
    # need one extra token so every position has a next-token label
    n = (len(flat) - 1) // seq_len
    ids = flat[: n * seq_len].reshape(n, seq_len).astype(np.int32)
    labels = flat[1 : n * seq_len + 1].reshape(n, seq_len).astype(np.int32)
    return ids, labels


def pack_documents(
    docs: Iterable[np.ndarray],
    seq_len: int,
    eos_id: int,
    pad_id: int = 0,
    mask_separators: bool = False,
) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Greedy first-fit packing: each document (+1 EOS) is placed whole into
    the first row with room; rows never split a document.  Returns
    ``(ids, labels, segment_ids)`` each ``[N, seq_len]``:

    - ``labels`` are next-token within each document, ``IGNORE`` (-100) on
      padding, on the EOS position itself (nothing follows it), and
      (optionally, ``mask_separators``) on the position that predicts EOS;
    - ``segment_ids`` number pieces within a row from 1 (0 = padding), the
      mask an attention kernel needs to block cross-document attention.

    Documents longer than ``seq_len`` are split into ``seq_len``-sized pieces
    first.  Crucially the split inserts NO fake EOS: labels are computed over
    the whole document before splitting, so a piece's last position predicts
    the document's true next token — the model is never taught that documents
    end at arbitrary ``seq_len`` boundaries."""
    pieces: List[Tuple[np.ndarray, np.ndarray]] = []
    for d in docs:
        d = np.asarray(d, np.int32).ravel()
        toks = np.concatenate([d, np.asarray([eos_id], np.int32)])
        labs = np.concatenate([toks[1:], np.asarray([IGNORE], np.int32)])
        if mask_separators and len(toks) >= 2:
            labs[len(toks) - 2] = IGNORE  # the position predicting EOS
        for i in range(0, len(toks), seq_len):
            pieces.append((toks[i : i + seq_len], labs[i : i + seq_len]))

    rows: List[List[Tuple[np.ndarray, np.ndarray]]] = []
    space: List[int] = []
    # first-fit over a bounded lookback of recently-opened rows: full
    # first-fit is O(pieces x rows) (quadratic at corpus scale); a window
    # keeps packing near-identical at O(pieces x window)
    window = 64
    for piece in pieces:
        need = len(piece[0])
        placed = False
        lo = max(0, len(rows) - window)
        for r in range(lo, len(rows)):
            if space[r] >= need:
                rows[r].append(piece)
                space[r] -= need
                placed = True
                break
        if not placed:
            rows.append([piece])
            space.append(seq_len - need)

    N = len(rows)
    ids = np.full((N, seq_len), pad_id, np.int32)
    labels = np.full((N, seq_len), IGNORE, np.int32)
    segs = np.zeros((N, seq_len), np.int32)
    for r, row_pieces in enumerate(rows):
        pos = 0
        for si, (ptoks, plabs) in enumerate(row_pieces, start=1):
            L = len(ptoks)
            ids[r, pos : pos + L] = ptoks
            labels[r, pos : pos + L] = plabs
            segs[r, pos : pos + L] = si
            pos += L
    return ids, labels, segs


def segment_positions(segment_ids: np.ndarray) -> np.ndarray:
    """Per-document RoPE positions from ``[N, S]`` segment ids: position =
    offset within the segment's contiguous run (0 on padding too).  The
    companion of :func:`pack_documents` every packed consumer needs."""
    segment_ids = np.asarray(segment_ids)
    S = segment_ids.shape[-1]
    start = np.zeros_like(segment_ids)
    changes = segment_ids[..., 1:] != segment_ids[..., :-1]
    start[..., 1:] = np.where(changes, np.arange(1, S), 0)
    start = np.maximum.accumulate(start, axis=-1)
    return (np.arange(S) - start).astype(np.int32)
