"""Data pipeline: native (C++) memory-mapped token-dataset loader with
deterministic DP sharding and background prefetch; numpy fallback with
identical semantics.  :class:`DevicePrefetcher` extends the overlap onto the
accelerator: batches are ``device_put`` against the step's shardings ahead
of the step that consumes them (``fit(prefetch=N)``)."""

from neuronx_distributed_tpu.data.loader import (
    TokenDataLoader,
    TokenDataset,
    read_token_file,
    write_token_file,
)
from neuronx_distributed_tpu.data.prefetch import DevicePrefetcher

__all__ = [
    "DevicePrefetcher",
    "TokenDataLoader",
    "TokenDataset",
    "read_token_file",
    "write_token_file",
]
