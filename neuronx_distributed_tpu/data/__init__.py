"""Data pipeline: native (C++) memory-mapped token-dataset loader with
deterministic DP sharding and background prefetch; numpy fallback with
identical semantics."""

from neuronx_distributed_tpu.data.loader import (
    TokenDataLoader,
    TokenDataset,
    read_token_file,
    write_token_file,
)

__all__ = [
    "TokenDataLoader",
    "TokenDataset",
    "read_token_file",
    "write_token_file",
]
