"""Device-prefetch input pipeline: stage batch N+1..N+depth onto the
accelerator while step N runs.

The reference framework gets input/compute overlap from torch-xla's
``MpDeviceLoader``/``ParallelLoader`` (a background thread feeding per-device
queues, SURVEY §L1); our ``fit()`` loop previously handed the jitted step a
*host* batch every iteration, so the step's first act on a real TPU was a
blocking host→device copy.  :class:`DevicePrefetcher` closes that gap
TPU-natively:

- a bounded background thread pulls from any step-indexed ``data(step)``
  callable (or an iterator adapter) and ``jax.device_put``'s each batch
  against the step's batch shardings — double/triple buffering is just
  ``depth=2``/``3``;
- delivery is **step-indexed and rewindable**: ``get(step)`` hands back the
  staged batch for exactly that step, and a non-sequential request (a
  resilience policy rolling the run back to an earlier step) flushes the
  staged pipeline and restarts staging at the requested step — exact-resume
  and rollback semantics are preserved, never approximated;
- queue-depth / staged-ahead gauges and rewind / staged counters land in the
  obs registry so the overlap is observable, not assumed;
- ``close()`` (or the context manager) drains the worker deterministically:
  no leaked thread, no stale staged batch — ``fit()`` closes it on every
  exit path including early stop and SIGTERM checkpointing.
"""

from __future__ import annotations

import queue
import threading
from typing import Any, Callable, Iterable, Optional

import jax

from neuronx_distributed_tpu.utils.logger import get_logger

logger = get_logger(__name__)

# metric names (the obs.schemas.REGISTRY_METRICS contract)
QUEUE_DEPTH = "data/prefetch_queue_depth"
STAGED_AHEAD = "data/prefetch_staged_ahead"
REWINDS_TOTAL = "data/prefetch_rewinds_total"
STAGED_TOTAL = "data/prefetch_batches_staged_total"
WAIT_MS = "data/prefetch_wait_ms"

_POLL_S = 0.05  # worker put/consumer get poll so close()/rewind never hang


class DevicePrefetcher:
    """Bounded background staging of ``data(step)`` batches onto devices.

    Args:
      source: ``source(step) -> host batch`` (step-indexed, the rewindable
        form ``fit`` prefers) or any iterable of batches (adapted; iterators
        deliver in order and cannot rewind).
      depth: staged-ahead bound (2 = double buffering, 3 = triple, ...).
      shardings: a pytree of ``jax.sharding.Sharding`` (or one sharding
        broadcast over the batch tree) for the staged ``device_put`` — pass
        the step's batch shardings so staged batches land exactly where the
        jitted step wants them; ``None`` stages to the default device.
      registry: an ``obs.MetricRegistry`` for the gauges/counters (optional).
      name: metric/thread-name prefix (default ``data``).

    ``get(step)`` blocks until that step's batch is staged (the wait is the
    pipeline's *observed* stall, exported as ``data/prefetch_wait_ms``).
    Exceptions from ``source`` (including ``StopIteration`` from an
    exhausted iterator) surface on the ``get`` that would have consumed the
    failing step."""

    def __init__(
        self,
        source: "Callable[[int], Any] | Iterable[Any]",
        *,
        depth: int = 2,
        shardings: Any = None,
        registry: Any = None,
        name: str = "data",
    ):
        if depth < 1:
            raise ValueError(f"prefetch depth must be >= 1, got {depth}")
        if callable(source):
            self._source = source
            self._rewindable = True
        else:
            it = iter(source)
            self._source = lambda step: next(it)
            self._rewindable = False
        self.depth = int(depth)
        self._shardings = shardings
        self._registry = registry
        self._name = name
        self._queue: "queue.Queue" = queue.Queue(maxsize=self.depth)
        self._lock = threading.Lock()
        self._gen = 0            # staging generation; a rewind bumps it
        self._thread: Optional[threading.Thread] = None
        self._next_out: Optional[int] = None  # step the consumer gets next
        self._staged_to = 0      # worker progress (gauge only)
        self._closed = False
        self.rewinds = 0
        if registry is not None:
            from neuronx_distributed_tpu.obs import MS_BUCKETS

            self._ms_buckets = MS_BUCKETS
            registry.gauge(QUEUE_DEPTH)
            registry.gauge(STAGED_AHEAD)
            registry.counter(REWINDS_TOTAL)
            registry.counter(STAGED_TOTAL)
            registry.histogram(WAIT_MS, MS_BUCKETS)

    # -- worker ------------------------------------------------------------

    def _stale(self, gen: int) -> bool:
        with self._lock:
            return self._closed or gen != self._gen

    def _offer(self, gen: int, item: tuple) -> bool:
        """Blocking put that abandons the item when the generation went
        stale (rewind/close) instead of wedging on a full queue."""
        while True:
            if self._stale(gen):
                return False
            try:
                self._queue.put(item, timeout=_POLL_S)
                return True
            except queue.Full:
                continue

    def _worker(self, gen: int, start: int) -> None:
        step = start
        while not self._stale(gen):
            try:
                batch = self._source(step)
                staged = (jax.device_put(batch) if self._shardings is None
                          else jax.device_put(batch, self._shardings))
            except BaseException as e:  # delivered to the consumer's get()
                self._offer(gen, (gen, step, None, e))
                return
            if not self._offer(gen, (gen, step, staged, None)):
                return
            with self._lock:
                self._staged_to = step + 1
            if self._registry is not None:
                self._registry.counter(STAGED_TOTAL).inc()
            step += 1

    # -- consumer ----------------------------------------------------------

    def _restart(self, step: int) -> None:
        """(Re)start staging at ``step``: bump the generation (the old
        worker sees it and exits), drop staged batches, spawn a worker."""
        with self._lock:
            was_running = self._thread is not None
            self._gen += 1
            gen = self._gen
            self._next_out = step
            self._staged_to = step
        self._drain()
        if was_running:
            self.rewinds += 1
            if self._registry is not None:
                self._registry.counter(REWINDS_TOTAL).inc()
            logger.info("prefetch[%s]: rewound staging to step %d",
                        self._name, step)
        self._thread = threading.Thread(
            target=self._worker, args=(gen, step),
            name=f"{self._name}-prefetch", daemon=True)
        self._thread.start()

    def _drain(self) -> None:
        while True:
            try:
                self._queue.get_nowait()
            except queue.Empty:
                return

    def get(self, step: int) -> Any:
        """The staged batch for exactly ``step``.  Sequential calls stream
        from the staged pipeline; a non-sequential step (policy rollback,
        or the very first call fixing the start step) rewinds/starts
        staging there."""
        if self._closed:
            raise RuntimeError(f"prefetch[{self._name}] is closed")
        if self._thread is None or step != self._next_out:
            if self._thread is not None and not self._rewindable:
                raise RuntimeError(
                    f"prefetch[{self._name}]: cannot rewind to step {step} "
                    f"(expected {self._next_out}): the source is an "
                    "iterator — rewinds need step-indexed data(step)")
            self._restart(step)
        import time as _time

        t0 = _time.perf_counter()
        while True:
            try:
                gen, s, staged, err = self._queue.get(timeout=_POLL_S)
            except queue.Empty:
                if self._thread is not None and not self._thread.is_alive() \
                        and self._queue.empty():
                    raise RuntimeError(
                        f"prefetch[{self._name}]: worker died without "
                        f"delivering step {step}")
                continue
            if gen != self._gen:
                continue  # staged before a rewind: stale, drop
            break
        wait_s = _time.perf_counter() - t0
        if err is not None:
            raise err
        assert s == step, f"prefetch ordering bug: got {s}, wanted {step}"
        self._next_out = step + 1
        if self._registry is not None:
            self._registry.gauge(QUEUE_DEPTH).set(self._queue.qsize())
            with self._lock:
                ahead = self._staged_to - (step + 1)
            self._registry.gauge(STAGED_AHEAD).set(max(ahead, 0))
            self._registry.histogram(WAIT_MS, self._ms_buckets).observe(
                wait_s * 1e3)
        return staged

    def close(self, timeout: float = 5.0) -> None:
        """Stop staging and join the worker.  Idempotent; after close the
        queue holds nothing (no stale staged batch can leak into a resumed
        run) and the thread is gone (asserted by the drain smoke tests)."""
        with self._lock:
            if self._closed:
                return
            self._closed = True
            self._gen += 1
        self._drain()  # unblock a worker stuck in put
        t = self._thread
        if t is not None:
            t.join(timeout=timeout)
            if t.is_alive():  # pragma: no cover - source wedged in user code
                logger.warning("prefetch[%s]: worker did not stop in %.1fs",
                               self._name, timeout)
            self._thread = None
        self._drain()  # whatever the worker put while we were joining
        if self._registry is not None:
            self._registry.gauge(QUEUE_DEPTH).set(0)
            self._registry.gauge(STAGED_AHEAD).set(0)

    def __enter__(self) -> "DevicePrefetcher":
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        self.close()
