"""Gradient norm / clipping utilities.

TPU-native counterpart of the reference's ``parallel_layers/grads.py``:

- ``get_grad_norm`` / ``clip_grad_norm`` (reference ``:29-190``): the
  reference spends most of its code classifying params into TP-duplicated vs
  TP-sharded vs PP-shared so each rank can correct its local partial norm
  (including a ``force_spmd`` mode that keeps every rank's graph identical,
  ``:103-129``).  Under GSPMD none of that exists: gradient pytrees are
  *logically global* arrays, so the norm is a plain reduction and XLA derives
  the cross-shard collectives from the shardings — every rank's graph is
  identical by construction.

- ``bucket_allreduce_gradients`` (reference ``:193-246``, reverse-order
  512 MB dtype-grouped buckets over the DP mesh): unnecessary here — data
  parallelism is the ``dp`` sharding of the batch dim, so the gradient psum
  over DP is inserted by autodiff/GSPMD inside the one jitted train step, and
  XLA's scheduler handles fusion/overlap of those collectives.

- ``allreduce_sequence_parallel_gradients`` (reference ``:249-264``): also
  unnecessary — norm/bias weights in SP regions are replicated params whose
  grad psum autodiff already emits.

The explicit shard_map path gets :func:`psum_over_data_parallel` for parity
with the reference's DP reduction when a user writes manual per-rank steps.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from neuronx_distributed_tpu.parallel.mesh import BATCH_AXES, manual_axis_size


def get_grad_norm(grads, norm_type: float = 2.0) -> jax.Array:
    """Global norm over a gradient pytree, accumulated in fp32
    (reference ``grads.py:29-138``)."""
    leaves = jax.tree.leaves(grads)
    if not leaves:
        return jnp.zeros((), jnp.float32)
    if norm_type == 2.0:
        return jnp.sqrt(
            sum(jnp.sum(jnp.square(g.astype(jnp.float32))) for g in leaves)
        )
    if norm_type == float("inf"):
        return jnp.max(
            jnp.stack([jnp.max(jnp.abs(g.astype(jnp.float32))) for g in leaves])
        )
    return (
        sum(jnp.sum(jnp.abs(g.astype(jnp.float32)) ** norm_type) for g in leaves)
    ) ** (1.0 / norm_type)


def clip_grad_norm(
    grads, max_norm: float, norm_type: float = 2.0, eps: float = 1e-6
) -> Tuple[jax.Array, jax.Array]:
    """Scale ``grads`` so their global norm is at most ``max_norm``; returns
    ``(clipped_grads, pre_clip_norm)`` (reference ``grads.py:141-190``,
    torch-style ``clip_coeff = max_norm / (norm + eps)`` capped at 1)."""
    norm = get_grad_norm(grads, norm_type)
    clip_coeff = jnp.minimum(max_norm / (norm + eps), 1.0)
    clipped = jax.tree.map(lambda g: (g.astype(jnp.float32) * clip_coeff).astype(g.dtype), grads)
    return clipped, norm


def psum_over_data_parallel(grads, mean: bool = True):
    """Explicit DP gradient reduction for shard_map training steps
    (the conjugate of the reference's ``bucket_allreduce_gradients``)."""
    n = 1
    for a in BATCH_AXES:
        n *= manual_axis_size(a)
    reduced = jax.tree.map(lambda g: lax.psum(g, BATCH_AXES), grads)
    if mean:
        reduced = jax.tree.map(lambda g: g / n, reduced)
    return reduced
