"""Parallel-state: the single global source of truth for the device mesh.

TPU-native replacement for the reference's process-group bookkeeping
(``neuronx-distributed/src/neuronx_distributed/parallel_layers/parallel_state.py:41-163``).
Where the reference builds c10d process groups with attached SPMD replica-group
lists (DP groups with stride tp, contiguous TP groups, strided PP groups), we
build one :class:`jax.sharding.Mesh` whose named axes *are* the replica groups:

======  =====================================================================
axis    meaning
======  =====================================================================
``dp``  data parallelism (gradient psum / ZeRO-1 state sharding)
``ep``  expert parallelism — a sub-axis of data parallelism along which MoE
        experts are sharded; dense models keep it at size 1
``pp``  pipeline parallelism (stage-sharded weights, ppermute transfers)
``cp``  context parallelism (ring-attention KV rotation; long-context)
``kvr`` KV-replication sub-axis of tensor parallelism — the mesh-native form
        of the reference's dedicated KV process groups
        (``modules/qkv_linear.py:26-62``): KV projections are *replicated*
        along ``kvr`` and sharded along ``tp``, so the KV gradient psum over
        the reference's KV-shared group becomes a GSPMD-inserted psum over
        ``kvr``
``tp``  tensor parallelism proper
======  =====================================================================

Megatron-style tensor parallel sharding always uses the *combined*
``TENSOR_AXES = ('kvr', 'tp')`` tuple so that when ``kv_size_multiplier == 1``
(the common case, axis size 1) nothing changes, and when it is > 1 the Q/gate
projections still shard over the full TP degree while KV shards only over
``tp``.  Axis order puts ``tp`` innermost so TP collectives ride the
fastest-varying (ICI-adjacent) devices, mirroring the reference's contiguous
TP groups (``parallel_state.py:109-122``).
"""

from __future__ import annotations

import dataclasses
import math
from contextlib import contextmanager
from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from neuronx_distributed_tpu.utils.logger import get_logger

logger = get_logger(__name__)

# Canonical axis names, outermost (slowest-varying / DCN-friendly) first.
DATA_AXIS = "dp"
EXPERT_AXIS = "ep"
PIPELINE_AXIS = "pp"
CONTEXT_AXIS = "cp"
KV_REPLICA_AXIS = "kvr"
TENSOR_AXIS = "tp"

MESH_AXES = (DATA_AXIS, EXPERT_AXIS, PIPELINE_AXIS, CONTEXT_AXIS, KV_REPLICA_AXIS, TENSOR_AXIS)

# Combined axis tuples used by layers/specs.
TENSOR_AXES = (KV_REPLICA_AXIS, TENSOR_AXIS)  # full TP degree = kvr * tp
BATCH_AXES = (DATA_AXIS, EXPERT_AXIS)  # full data-parallel degree = dp * ep
# Sequence-parallel regions shard the sequence axis over the full TP degree
# (the reference's Megatron-SP, mappings.py:198-250); with context parallelism
# the sequence is additionally sharded over cp.
SEQUENCE_AXES = (CONTEXT_AXIS, KV_REPLICA_AXIS, TENSOR_AXIS)


@dataclasses.dataclass(frozen=True)
class MeshConfig:
    """Sizes of every parallel dimension.

    ``data_parallel_size`` may be left as ``None`` to infer it from the device
    count, mirroring the reference's behaviour where DP size is always
    ``world // (tp * pp)`` (``parallel_state.py:74-88``).
    """

    tensor_parallel_size: int = 1
    pipeline_parallel_size: int = 1
    context_parallel_size: int = 1
    expert_parallel_size: int = 1
    kv_size_multiplier: int = 1
    data_parallel_size: Optional[int] = None

    def __post_init__(self):
        for f in dataclasses.fields(self):
            v = getattr(self, f.name)
            if v is not None and v < 1:
                raise ValueError(f"{f.name} must be >= 1, got {v}")
        if self.tensor_parallel_size % self.kv_size_multiplier != 0:
            raise ValueError(
                f"tensor_parallel_size ({self.tensor_parallel_size}) must be divisible by "
                f"kv_size_multiplier ({self.kv_size_multiplier})"
            )

    @property
    def model_parallel_size(self) -> int:
        return (
            self.tensor_parallel_size
            * self.pipeline_parallel_size
            * self.context_parallel_size
            * self.expert_parallel_size
        )


class _MeshState:
    """Module-level singleton holding the live mesh, like the reference's
    module globals (``parallel_state.py:22-38``)."""

    def __init__(self):
        self.mesh: Optional[Mesh] = None
        self.config: Optional[MeshConfig] = None

    def clear(self):
        self.mesh = None
        self.config = None


_STATE = _MeshState()


def _build_device_array(devices: Sequence[jax.Device], shape: Sequence[int]) -> np.ndarray:
    """Arrange devices into the mesh shape.

    On real TPU slices, delegate to ``mesh_utils`` so the mesh respects
    physical topology: a single slice uses ``create_device_mesh`` (ICI-aware
    axis assignment), and a MULTI-slice job uses ``create_hybrid_device_mesh``
    with the data-parallel axis split across slices — so only dp traffic
    (gradient psum, once per step) rides the slow DCN links while tp/cp/pp
    collectives stay on intra-slice ICI.  This is the mesh-layout form of the
    reference's "EFA across nodes, NeuronLink within" topology
    (``run_llama_70b_tp_pp.sh:7-15``); here the transport choice falls out of
    device order instead of env flags.  For CPU/virtual devices a plain
    reshape preserves rank-contiguity (TP innermost), matching the
    reference's contiguous-TP / strided-DP group construction.
    """
    devices = list(devices)
    if math.prod(shape) != len(devices):
        raise ValueError(f"mesh shape {tuple(shape)} does not match device count {len(devices)}")
    if devices and devices[0].platform == "tpu" and len(devices) > 1:
        n_slices = len({getattr(d, "slice_index", 0) for d in devices})
        try:
            from jax.experimental import mesh_utils

            if n_slices > 1 and shape[0] % n_slices == 0:
                dcn_shape = (n_slices,) + (1,) * (len(shape) - 1)
                local_shape = (shape[0] // n_slices, *shape[1:])
                return mesh_utils.create_hybrid_device_mesh(
                    local_shape, dcn_shape, devices=devices
                )
            if n_slices > 1:
                # dp cannot absorb the slice boundary (e.g. dp=1, pp across
                # slices — the reference's 70B topology): a legitimate
                # layout, just with model-parallel traffic on DCN
                logger.warning(
                    "dp=%d not divisible by %d slices; letting "
                    "create_device_mesh choose the layout (some model-"
                    "parallel collectives will cross DCN)", shape[0], n_slices,
                )
            return mesh_utils.create_device_mesh(tuple(shape), devices=devices)
        except Exception as e:  # pragma: no cover - topology helpers can be picky
            logger.warning("mesh_utils device-mesh construction failed (%s); falling back to reshape", e)
    return np.asarray(devices).reshape(tuple(shape))


def initialize_model_parallel(
    tensor_parallel_size: int = 1,
    pipeline_parallel_size: int = 1,
    context_parallel_size: int = 1,
    expert_parallel_size: int = 1,
    kv_size_multiplier: int = 1,
    data_parallel_size: Optional[int] = None,
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """Build and install the global mesh.

    TPU-native analogue of ``parallel_state.initialize_model_parallel``
    (``parallel_state.py:41-163``): instead of constructing DP/TP/PP process
    groups with replica-group lists, one named mesh encodes the full topology
    and XLA derives every collective's replica groups from axis names.
    """
    if _STATE.mesh is not None:
        raise RuntimeError("model parallel is already initialized; call destroy_model_parallel() first")

    # RNG discipline (the framework's stance on the reference's TP-aware
    # RNG tracker, ``parallel_layers/random.py:100-127``): partitionable
    # threefry makes every jax.random draw sharding-invariant AND cheap
    # under GSPMD — each shard generates only its slice of the global
    # stream, yet the values equal the single-device run.  The reference
    # forks per-TP-rank seeds so each rank drops its own shard elements
    # independently; here the one-key global-array semantics gives each
    # shard its own mask slice for free, with no rank-seed bookkeeping.
    # Pinned centrally so dropout/noise is reproducible across tp/dp/mesh
    # choices (tests/test_rng_dropout.py).
    jax.config.update("jax_threefry_partitionable", True)

    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    cfg = MeshConfig(
        tensor_parallel_size=tensor_parallel_size,
        pipeline_parallel_size=pipeline_parallel_size,
        context_parallel_size=context_parallel_size,
        expert_parallel_size=expert_parallel_size,
        kv_size_multiplier=kv_size_multiplier,
        data_parallel_size=data_parallel_size,
    )
    mp = cfg.model_parallel_size
    if n % mp != 0:
        raise ValueError(f"device count {n} not divisible by model parallel size {mp}")
    dp = n // mp
    # ``data_parallel_size`` means the FULL data-parallel degree (dp * ep),
    # matching what get_data_parallel_size() reports.
    if data_parallel_size is not None and data_parallel_size != dp * expert_parallel_size:
        raise ValueError(
            f"explicit data_parallel_size {data_parallel_size} inconsistent with "
            f"device count {n}: expected {dp * expert_parallel_size} "
            f"(= {n} / (tp*pp*cp) with ep={expert_parallel_size})"
        )
    cfg = dataclasses.replace(cfg, data_parallel_size=dp * expert_parallel_size)

    shape = (
        dp,
        expert_parallel_size,
        pipeline_parallel_size,
        context_parallel_size,
        kv_size_multiplier,
        tensor_parallel_size // kv_size_multiplier,
    )
    mesh = Mesh(_build_device_array(devices, shape), MESH_AXES)
    _STATE.mesh = mesh
    _STATE.config = cfg
    logger.info(
        "initialized mesh: dp=%d ep=%d pp=%d cp=%d kvr=%d tp=%d over %d devices",
        *shape,
        n,
    )
    return mesh


def destroy_model_parallel() -> None:
    """Tear down the global mesh (reference: ``parallel_state.py:destroy_model_parallel``)."""
    _STATE.clear()


def model_parallel_is_initialized() -> bool:
    return _STATE.mesh is not None


def get_mesh() -> Mesh:
    if _STATE.mesh is None:
        raise RuntimeError("model parallel is not initialized; call initialize_model_parallel() first")
    return _STATE.mesh


def get_mesh_config() -> MeshConfig:
    if _STATE.config is None:
        raise RuntimeError("model parallel is not initialized")
    return _STATE.config


def config_from_mesh(mesh: Mesh) -> MeshConfig:
    """Derive a MeshConfig from a mesh's axis sizes."""
    return MeshConfig(
        tensor_parallel_size=mesh.shape[KV_REPLICA_AXIS] * mesh.shape[TENSOR_AXIS],
        pipeline_parallel_size=mesh.shape[PIPELINE_AXIS],
        context_parallel_size=mesh.shape[CONTEXT_AXIS],
        expert_parallel_size=mesh.shape[EXPERT_AXIS],
        kv_size_multiplier=mesh.shape[KV_REPLICA_AXIS],
        data_parallel_size=mesh.shape[DATA_AXIS] * mesh.shape[EXPERT_AXIS],
    )


@contextmanager
def mesh_context(mesh: Mesh, config: Optional[MeshConfig] = None):
    """Temporarily install ``mesh`` as the global mesh (used by tests and the
    inference tracer, which the reference handles with set/unset override
    hooks, ``parallel_state.py:193-210``)."""
    prev_mesh, prev_cfg = _STATE.mesh, _STATE.config
    _STATE.mesh = mesh
    _STATE.config = config if config is not None else config_from_mesh(mesh)
    try:
        yield mesh
    finally:
        _STATE.mesh, _STATE.config = prev_mesh, prev_cfg


# ---------------------------------------------------------------------------
# Size / rank helpers (reference: get_*_parallel_{size,rank}).
# Sizes are host-side ints from the mesh; ranks only exist inside shard_map,
# via jax.lax.axis_index.
# ---------------------------------------------------------------------------


def _axis_size(mesh: Optional[Mesh], *axes: str) -> int:
    mesh = mesh if mesh is not None else get_mesh()
    return int(math.prod(mesh.shape[a] for a in axes))


def manual_axis_size(axis_name: str) -> int:
    """Trace-time size of a manual (shard_map) axis, version-portable:
    jax >= 0.5 has ``lax.axis_size``; older jax folds ``psum(1, axis)`` to
    the same static constant."""
    if hasattr(jax.lax, "axis_size"):
        return jax.lax.axis_size(axis_name)
    return int(jax.lax.psum(1, axis_name))


def get_tensor_parallel_size(mesh: Optional[Mesh] = None) -> int:
    """Full TP degree, kvr * tp (reference: ``get_tensor_model_parallel_size``)."""
    return _axis_size(mesh, *TENSOR_AXES)


def get_pipeline_parallel_size(mesh: Optional[Mesh] = None) -> int:
    return _axis_size(mesh, PIPELINE_AXIS)


def get_data_parallel_size(mesh: Optional[Mesh] = None) -> int:
    """Full data-parallel degree, dp * ep."""
    return _axis_size(mesh, *BATCH_AXES)


def get_context_parallel_size(mesh: Optional[Mesh] = None) -> int:
    return _axis_size(mesh, CONTEXT_AXIS)


def get_expert_parallel_size(mesh: Optional[Mesh] = None) -> int:
    return _axis_size(mesh, EXPERT_AXIS)


def get_kv_size_multiplier(mesh: Optional[Mesh] = None) -> int:
    return _axis_size(mesh, KV_REPLICA_AXIS)


def tensor_parallel_rank() -> jax.Array:
    """Traced TP rank; valid only inside shard_map over the global mesh."""
    kvr = jax.lax.axis_index(KV_REPLICA_AXIS)
    tp = jax.lax.axis_index(TENSOR_AXIS)
    return kvr * manual_axis_size(TENSOR_AXIS) + tp


def named_sharding(*spec) -> NamedSharding:
    """Shorthand: NamedSharding over the global mesh."""
    return NamedSharding(get_mesh(), P(*spec))


def strip_axes_from_spec(spec: P, drop: frozenset) -> P:
    """Remove the given mesh axes from a PartitionSpec (tuple entries keep
    their remaining axes; emptied entries become None)."""

    def strip(e):
        if e is None:
            return None
        if isinstance(e, (tuple, list)):
            kept = tuple(a for a in e if a not in drop)
            return kept or None
        return None if e in drop else e

    return P(*(strip(e) for e in spec))


_AXIS_ENV_WARNED = False


def ambient_manual_axes() -> frozenset:
    """Mesh axes already *manual* in the enclosing trace context.

    Inside a ``shard_map`` body the manual axes are bound in JAX's axis
    environment (that's what makes ``lax.psum(x, 'dp')`` legal there), so the
    environment reveals which axes an enclosing shard_map — e.g. the 1F1B
    engine's manual ``(dp, ep, pp)`` — already owns.  Two consumers need
    this: a nested shard_map must go manual over exactly the *rest* (Mosaic
    kernels refuse Auto axes; re-declaring an already-manual axis is an
    error — ring/flash attention), and GSPMD sharding constraints inside the
    body may only reference the remaining *auto* axes (MoE expert specs).
    """
    try:
        from jax._src.core import get_axis_env

        return frozenset(get_axis_env().axis_sizes) & frozenset(MESH_AXES)
    except Exception as e:  # pragma: no cover - internals moved in a JAX bump
        # Loud, not fatal: top-level callers still work with the empty set,
        # but nested use (inside the 1F1B engine) would re-declare or
        # re-constrain the outer manual axes and fail — log the real cause.
        global _AXIS_ENV_WARNED
        if not _AXIS_ENV_WARNED:
            _AXIS_ENV_WARNED = True
            logger.warning(
                "jax._src.core.get_axis_env unavailable (%s): cannot detect "
                "enclosing shard_map manual axes; flash/ring attention or MoE "
                "inside the pipeline engine may fail to trace on this JAX "
                "version", e,
            )
        return frozenset()


def rmsg(msg: str) -> str:
    """Rank-annotated log message (reference: ``parallel_state.py:394-406``).

    Under SPMD-jit there is no per-device python rank, so we annotate with the
    host process index instead.
    """
    return f"[proc_{jax.process_index()}] {msg}"
