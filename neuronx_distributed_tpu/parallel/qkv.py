"""GQA QKV projection with KV-head replication across a TP sub-axis.

TPU-native re-design of the reference's ``GQAQKVColumnParallelLinear``
(``modules/qkv_linear.py``).  The reference solves "num KV heads < TP degree"
by physically repeating the KV weight ``kv_size_multiplier`` times before
sharding and summing KV grads over a dedicated KV-shared process group of
stride ``tp/kv_size_multiplier`` (``qkv_linear.py:26-62,78-118,208-222``).

Here no weight is ever repeated.  The mesh factors the full TP degree into
``kvr × tp`` (``parallel/mesh.py``), and:

- **Q** kernels shard their head dim over ``('tp', 'kvr')`` — tp-major, so
  device ``(kvr=o, tp=i)`` holds the q-head block ``i*kvr_size + o``;
- **K/V** kernels shard their head dim over ``'tp'`` only, replicated along
  ``kvr``.

With ``groups = num_heads // num_kv_heads`` q-heads per kv-head, device
``(o, i)`` holds q heads ``[i*g + o*g/kvr, ...)`` — exactly the q heads whose
kv head is head ``i``, the same pairing the reference builds with strided
KV groups.  Attention then needs zero cross-device communication, and the
reference's KV-grad correction (psum over the KV group + divide by the
multiplier) is what GSPMD derives automatically for a kvr-replicated kernel.
"""

from __future__ import annotations

from typing import Any, Callable, Tuple

import jax
import jax.numpy as jnp
from flax import linen as nn

from neuronx_distributed_tpu.parallel.layers import shard_activation, trailing_spec
from neuronx_distributed_tpu.parallel.mesh import (
    KV_REPLICA_AXIS,
    SEQUENCE_AXES,
    TENSOR_AXIS,
    get_kv_size_multiplier,
    get_tensor_parallel_size,
    model_parallel_is_initialized,
)

# Head-dim sharding axes for Q (tp-major: kv-group-major ordering) and KV.
Q_HEAD_AXES = (TENSOR_AXIS, KV_REPLICA_AXIS)
KV_HEAD_AXES = TENSOR_AXIS

Dtype = Any
Initializer = Callable[..., jax.Array]


def validate_gqa_sharding(num_heads: int, num_kv_heads: int) -> None:
    """Check head counts against the live mesh, guiding kv_size_multiplier
    choice (the reference validates in ``qkv_linear.py:363-380``)."""
    if not model_parallel_is_initialized():
        return
    tp_full = get_tensor_parallel_size()
    kvr = get_kv_size_multiplier()
    tp_inner = tp_full // kvr
    if num_heads % tp_full != 0:
        raise ValueError(f"num_heads={num_heads} not divisible by TP degree {tp_full}")
    if num_kv_heads % tp_inner != 0:
        raise ValueError(
            f"num_kv_heads={num_kv_heads} not divisible by tp={tp_inner} (= TP degree "
            f"{tp_full} / kv_size_multiplier {kvr}); initialize the mesh with "
            f"kv_size_multiplier={tp_full // num_kv_heads if num_kv_heads and tp_full % num_kv_heads == 0 else '<tp/num_kv_heads>'}"
        )


class GQAQKVColumnParallelLinear(nn.Module):
    """Computes Q, K, V projections with GQA-aware sharding.

    Returns ``(q, k, v)`` shaped ``[..., num_heads, head_dim]`` /
    ``[..., num_kv_heads, head_dim]`` (reference fwd computes the three
    separately too, ``qkv_linear.py:181-185``)."""

    num_heads: int
    num_kv_heads: int
    head_dim: int
    use_bias: bool = False
    sequence_parallel: bool = False
    # LoRA on the q/k/v projections: per-projection A ``[in, r]`` replicated,
    # B shaped/sharded like the projection's head layout, zero-initialized.
    lora_rank: int = 0
    lora_alpha: float = 16.0
    lora_targets: Tuple[str, ...] = ("q", "v")  # the standard LoRA targets
    dtype: Dtype = jnp.bfloat16
    param_dtype: Dtype = jnp.float32
    kernel_init: Initializer = nn.initializers.lecun_normal()
    bias_init: Initializer = nn.initializers.zeros_init()

    @nn.compact
    def __call__(self, x: jax.Array) -> Tuple[jax.Array, jax.Array, jax.Array]:
        if self.num_heads % self.num_kv_heads != 0:
            raise ValueError(
                f"num_heads={self.num_heads} not divisible by num_kv_heads={self.num_kv_heads}"
            )
        validate_gqa_sharding(self.num_heads, self.num_kv_heads)
        in_features = x.shape[-1]

        wq = self.param(
            "q_kernel",
            nn.with_partitioning(self.kernel_init, (None, Q_HEAD_AXES, None)),
            (in_features, self.num_heads, self.head_dim),
            self.param_dtype,
        )
        wk = self.param(
            "k_kernel",
            nn.with_partitioning(self.kernel_init, (None, KV_HEAD_AXES, None)),
            (in_features, self.num_kv_heads, self.head_dim),
            self.param_dtype,
        )
        wv = self.param(
            "v_kernel",
            nn.with_partitioning(self.kernel_init, (None, KV_HEAD_AXES, None)),
            (in_features, self.num_kv_heads, self.head_dim),
            self.param_dtype,
        )

        x = x.astype(self.dtype)
        if self.sequence_parallel:
            x = shard_activation(x, trailing_spec(x.ndim, seq=SEQUENCE_AXES))

        def proj(w, head_axes, name):
            y = jnp.einsum("...h,hnd->...nd", x, jnp.asarray(w, self.dtype),
                           preferred_element_type=self.dtype)
            # head dim sits at -2 ([..., n_heads, head_dim])
            y = shard_activation(y, trailing_spec(y.ndim, seq=head_axes))
            if self.lora_rank > 0 and name in self.lora_targets:
                r = self.lora_rank
                n_heads = w.shape[1]
                a = self.param(
                    f"lora_a_{name}",
                    nn.with_partitioning(nn.initializers.lecun_normal(), (None, None)),
                    (in_features, r), self.param_dtype,
                )
                b = self.param(
                    f"lora_b_{name}",
                    nn.with_partitioning(nn.initializers.zeros_init(),
                                         (None, head_axes, None)),
                    (r, n_heads, self.head_dim), self.param_dtype,
                )
                xa = jnp.einsum("...h,hr->...r", x, jnp.asarray(a, self.dtype),
                                preferred_element_type=self.dtype)
                delta = jnp.einsum("...r,rnd->...nd", xa, jnp.asarray(b, self.dtype),
                                   preferred_element_type=self.dtype)
                y = y + (self.lora_alpha / r) * delta
            return y

        q = proj(wq, Q_HEAD_AXES, "q")
        k = proj(wk, KV_HEAD_AXES, "k")
        v = proj(wv, KV_HEAD_AXES, "v")

        if self.use_bias:
            bq = self.param(
                "q_bias",
                nn.with_partitioning(self.bias_init, (Q_HEAD_AXES, None)),
                (self.num_heads, self.head_dim),
                self.param_dtype,
            )
            bk = self.param(
                "k_bias",
                nn.with_partitioning(self.bias_init, (KV_HEAD_AXES, None)),
                (self.num_kv_heads, self.head_dim),
                self.param_dtype,
            )
            bv = self.param(
                "v_bias",
                nn.with_partitioning(self.bias_init, (KV_HEAD_AXES, None)),
                (self.num_kv_heads, self.head_dim),
                self.param_dtype,
            )
            q = q + jnp.asarray(bq, self.dtype)
            k = k + jnp.asarray(bk, self.dtype)
            v = v + jnp.asarray(bv, self.dtype)
        return q, k, v
