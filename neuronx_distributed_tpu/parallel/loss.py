"""Vocab-parallel cross entropy.

TPU-native re-design of the reference's ``_ParallelCrossEntropy``
(``parallel_layers/loss_functions.py:17-135``): the vocab dim of the logits is
sharded across TP, and the loss is computed without ever materializing the
full-vocab softmax on one device.

Two paths:

- :func:`vocab_parallel_cross_entropy` — explicit shard_map form with
  ``custom_vjp``: psum-MAX of the logit max, arithmetic target masking (no
  boolean indexing — XLA-friendly, same trick as reference ``:37-39``),
  psum-SUM of predicted logit and sum-exp, label smoothing, and a
  softmax-minus-one-hot backward (reference ``:103-130``).
- :func:`parallel_cross_entropy` — GSPMD form for use directly under jit:
  numerically identical math on the globally-shaped logits with a
  vocab-sharding constraint; XLA derives the same collectives.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from neuronx_distributed_tpu.parallel.mappings import AxisNames, axis_rank, axis_size, resolve_axes as _axes
from neuronx_distributed_tpu.parallel.layers import shard_activation, trailing_spec
from neuronx_distributed_tpu.parallel.mesh import TENSOR_AXES


# ---------------------------------------------------------------------------
# Explicit shard_map path
# ---------------------------------------------------------------------------


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3))
def vocab_parallel_cross_entropy(
    logits: jax.Array,
    targets: jax.Array,
    label_smoothing: float = 0.0,
    axis_name: Optional[AxisNames] = None,
) -> jax.Array:
    """Per-token NLL over vocab-sharded logits, inside shard_map.

    Args:
      logits: ``[..., vocab/TP]`` local logits shard (any leading dims).
      targets: ``[...]`` integer class ids, replicated across TP.
    Returns per-token loss ``[...]`` (replicated across TP).
    """
    loss, _ = _vp_ce_fwd(logits, targets, label_smoothing, axis_name)
    return loss


def _vp_ce_core(logits, targets, label_smoothing, axis_name):
    ax = _axes(axis_name)
    n = axis_size(ax)
    v_local = logits.shape[-1]
    vocab = v_local * n
    start = axis_rank(ax) * v_local

    logits = logits.astype(jnp.float32)
    # all-reduce MAX for numerical stability (reference :17-22)
    m = lax.pmax(jnp.max(logits, axis=-1), ax)
    shifted = logits - m[..., None]

    # arithmetic target masking (reference :37-39)
    local_idx = targets - start
    in_range = (local_idx >= 0) & (local_idx < v_local)
    clipped = jnp.clip(local_idx, 0, v_local - 1)
    pred_local = jnp.take_along_axis(shifted, clipped[..., None], axis=-1)[..., 0]
    pred_local = jnp.where(in_range, pred_local, 0.0)
    pred = lax.psum(pred_local, ax)  # all-reduce SUM (reference :55-60)

    exp_shifted = jnp.exp(shifted)
    sum_exp = lax.psum(jnp.sum(exp_shifted, axis=-1), ax)  # reference :61-71
    log_z = jnp.log(sum_exp)
    nll = log_z - pred

    if label_smoothing > 0.0:
        # smoothed loss mixes in the mean log-prob over the full vocab
        # (reference :80-96)
        mean_shifted = lax.psum(jnp.sum(shifted, axis=-1), ax) / vocab
        smooth = log_z - mean_shifted
        loss = (1.0 - label_smoothing) * nll + label_smoothing * smooth
    else:
        loss = nll
    residuals = (exp_shifted, sum_exp, clipped, in_range)
    return loss, residuals


def _vp_ce_fwd(logits, targets, label_smoothing, axis_name):
    loss, residuals = _vp_ce_core(logits, targets, label_smoothing, axis_name)
    # zero-size marker carries the primal dtype (a raw dtype is not a JAX type)
    return loss, (residuals, jnp.zeros((0,), logits.dtype))


def _vp_ce_bwd(label_smoothing, axis_name, carry, g):
    (exp_shifted, sum_exp, clipped, in_range), dtype_marker = carry
    in_dtype = dtype_marker.dtype
    ax = _axes(axis_name)
    n = axis_size(ax)
    v_local = exp_shifted.shape[-1]
    vocab = v_local * n

    softmax = exp_shifted / sum_exp[..., None]
    # one-hot of the local target index, zeroed when the target lives on
    # another shard (reference :103-130)
    onehot = jax.nn.one_hot(clipped, v_local, dtype=softmax.dtype)
    onehot = onehot * in_range[..., None].astype(softmax.dtype)
    if label_smoothing > 0.0:
        grad_target = (1.0 - label_smoothing) * onehot + label_smoothing / vocab
    else:
        grad_target = onehot
    dlogits = (softmax - grad_target) * g[..., None]
    return dlogits.astype(in_dtype), None


vocab_parallel_cross_entropy.defvjp(_vp_ce_fwd, _vp_ce_bwd)


# ---------------------------------------------------------------------------
# GSPMD path
# ---------------------------------------------------------------------------


def parallel_cross_entropy(
    logits: jax.Array, targets: jax.Array, label_smoothing: float = 0.0
) -> jax.Array:
    """Cross entropy over globally-shaped, vocab-sharded logits under jit.

    The vocab-dim sharding constraint makes XLA compute the max / sum-exp /
    predicted-logit reductions with the same TP collectives the explicit path
    issues by hand (the lm-head emits vocab-sharded logits via
    ``ColumnParallelLinear(gather_output=False)``; reference usage
    ``modeling_llama_nxd.py:681-699``)."""
    logits = shard_activation(logits, trailing_spec(logits.ndim, last=TENSOR_AXES))
    logits = logits.astype(jnp.float32)
    m = jax.lax.stop_gradient(jnp.max(logits, axis=-1, keepdims=True))
    shifted = logits - m
    log_z = jnp.log(jnp.sum(jnp.exp(shifted), axis=-1))
    # Clip so out-of-range ids (e.g. -100 ignore labels) stay finite; callers
    # mask those positions out of the mean themselves.
    safe_targets = jnp.clip(targets, 0, logits.shape[-1] - 1)
    pred = jnp.take_along_axis(shifted, safe_targets[..., None], axis=-1)[..., 0]
    nll = log_z - pred
    if label_smoothing > 0.0:
        smooth = log_z - jnp.mean(shifted, axis=-1)
        return (1.0 - label_smoothing) * nll + label_smoothing * smooth
    return nll
