"""Expert-parallel Mixture-of-Experts layer over the ``ep`` mesh axis.

The reference has NO MoE/expert parallelism anywhere (SURVEY §2.10: "EP —
Absent"); the mesh here carries a first-class ``ep`` axis (a sub-axis of data
parallelism, ``parallel/mesh.py``), and this module makes it real — beyond-
parity capability, like ring-attention CP.

TPU-native formulation: the GShard/Switch dense-dispatch pattern —
routing becomes two einsums against a one-hot dispatch tensor, so the
all-to-alls are GSPMD-inserted reshards between the token-sharded and
expert-sharded layouts instead of hand-written ``all_to_all`` calls, and
everything stays static-shaped (capacity-bounded) for jit:

1. router probs ``[N, E]`` (fp32 softmax);
2. top-k choice per token, position-in-expert by cumulative sum, tokens
   beyond ``capacity`` dropped (their combine weight is zero — standard
   capacity-factor semantics);
3. ``dispatch [N, E, C]`` one-hot and ``combine = dispatch * gate``;
4. ``xe = einsum('nh,nec->ech', x, dispatch)`` — result sharded ``e→ep``
   (the "all-to-all" to expert-major layout);
5. per-expert fused gate-up/down FFN, vmapped over local experts, inner
   dims TP-sharded exactly like the dense MLP;
6. ``y = einsum('ech,nec->nh', ye, combine)`` — back to token-major.

The load-balancing auxiliary loss is the Switch-Transformer form
``E * sum_e(frac_tokens_e * mean_prob_e)`` (=1 at perfect balance).
"""

from __future__ import annotations

from typing import Any, Callable, Tuple

import jax
import jax.numpy as jnp
from flax import linen as nn

from neuronx_distributed_tpu.parallel.layers import shard_activation
from neuronx_distributed_tpu.parallel.mesh import (
    BATCH_AXES,
    EXPERT_AXIS,
    TENSOR_AXES,
    ambient_manual_axes,
    strip_axes_from_spec,
)
from jax.sharding import PartitionSpec as P


def _auto_spec(*entries) -> P:
    """PartitionSpec with any ambient-*manual* mesh axes removed.

    Inside the 1F1B engine's partial-manual shard_map (manual ``dp/ep/pp``)
    GSPMD sharding constraints may only reference the remaining auto axes;
    a manual axis in a constraint is an error.  Dropping it is also the
    semantically right thing: under the engine the batch is already split
    per (dp, ep) rank, so ``ep`` degenerates to pure data parallelism and
    expert weights are simply replicated within the stage."""
    return strip_axes_from_spec(P(*entries), ambient_manual_axes())

Dtype = Any
Initializer = Callable[..., jax.Array]


def load_balancing_loss(probs: jax.Array, expert_mask: jax.Array) -> jax.Array:
    """Switch aux loss: ``E * sum_e(fraction_routed_e * mean_router_prob_e)``.
    ``probs [N, E]`` fp32 router probabilities, ``expert_mask [N, E]`` 0/1
    top-k selections (pre-capacity)."""
    E = probs.shape[-1]
    frac = jnp.mean(expert_mask.astype(jnp.float32), axis=0)
    mean_p = jnp.mean(probs, axis=0)
    return E * jnp.sum(frac * mean_p)


class ExpertParallelMLP(nn.Module):
    """Top-k routed MoE FFN; experts sharded over ``ep``, each expert's
    hidden dim over the TP axes (the dense MLP's sharding, per expert).

    Input/output ``[..., hidden]``; returns ``(y, aux_loss)``.
    """

    num_experts: int
    intermediate_size: int
    top_k: int = 2
    capacity_factor: float = 1.25
    dtype: Dtype = jnp.bfloat16
    param_dtype: Dtype = jnp.float32
    kernel_init: Initializer = nn.initializers.lecun_normal()

    @nn.compact
    def __call__(self, x: jax.Array) -> Tuple[jax.Array, jax.Array]:
        if self.top_k > self.num_experts:
            raise ValueError(f"top_k={self.top_k} > num_experts={self.num_experts}")
        *lead, H = x.shape
        E, I, K = self.num_experts, self.intermediate_size, self.top_k
        xt = x.reshape(-1, H)
        N = xt.shape[0]
        # static capacity: ceil(K * N / E * factor), at least K, multiple of 4
        cap = max(int(self.capacity_factor * K * N / E + 0.999), K)
        cap = min(-(-cap // 4) * 4, N)

        router = self.param(
            "router", nn.with_partitioning(self.kernel_init, (None, None)),
            (H, E), self.param_dtype,
        )
        wi = self.param(
            "gate_up",
            nn.with_partitioning(self.kernel_init, (EXPERT_AXIS, None, None, TENSOR_AXES)),
            (E, H, 2, I), self.param_dtype,
        )
        wo = self.param(
            "down",
            nn.with_partitioning(self.kernel_init, (EXPERT_AXIS, TENSOR_AXES, None)),
            (E, I, H), self.param_dtype,
        )

        # -- routing (fp32) --------------------------------------------------
        logits = jnp.einsum(
            "nh,he->ne", xt.astype(jnp.float32), router.astype(jnp.float32)
        )
        probs = jax.nn.softmax(logits, axis=-1)  # [N, E]

        gate_vals, expert_idx = jax.lax.top_k(probs, K)  # [N, K]
        onehot = jax.nn.one_hot(expert_idx, E, dtype=jnp.float32)  # [N, K, E]
        expert_mask = jnp.max(onehot, axis=1)  # [N, E] (for the aux loss)
        aux = load_balancing_loss(probs, expert_mask)

        # position of each (token, choice) within its expert's buffer:
        # cumulative count over tokens, k-th choices ranked after (k-1)-th
        # (the GShard priority convention)
        flat = onehot.transpose(1, 0, 2).reshape(K * N, E)  # k-major
        pos_flat = jnp.cumsum(flat, axis=0) - flat  # [K*N, E]
        pos = pos_flat.reshape(K, N, E).transpose(1, 0, 2)  # [N, K, E]
        pos_in_expert = jnp.sum(pos * onehot, axis=-1)  # [N, K]
        keep = pos_in_expert < cap  # capacity drop
        gate_vals = gate_vals * keep

        # normalize kept gates per token (Mixtral convention); fp32
        denom = jnp.maximum(jnp.sum(gate_vals, axis=-1, keepdims=True), 1e-9)
        gate_vals = gate_vals / denom

        # dispatch [N, E, C] / combine [N, E, C]
        pos_oh = jax.nn.one_hot(
            jnp.where(keep, pos_in_expert, cap).astype(jnp.int32), cap,
            dtype=jnp.float32,
        )  # [N, K, C] (dropped -> all-zero row)
        dispatch = jnp.einsum("nke,nkc->nec", onehot, pos_oh)
        combine = jnp.einsum("nke,nkc,nk->nec", onehot, pos_oh, gate_vals)

        # -- expert compute ----------------------------------------------------
        xe = jnp.einsum(
            "nh,nec->ech", xt.astype(self.dtype), dispatch.astype(self.dtype),
            preferred_element_type=self.dtype,
        )
        # expert-major layout: experts over ep, tokens replicated within
        xe = shard_activation(xe, _auto_spec(EXPERT_AXIS, None, None))

        def ffn(x_e, wi_e, wo_e):
            gu = jnp.einsum("ch,hfi->cfi", x_e, wi_e.astype(self.dtype),
                            preferred_element_type=self.dtype)
            h = jax.nn.silu(gu[:, 0, :]) * gu[:, 1, :]
            h = shard_activation(h, _auto_spec(None, TENSOR_AXES))
            return jnp.einsum("ci,ih->ch", h, wo_e.astype(self.dtype),
                              preferred_element_type=self.dtype)

        ye = jax.vmap(ffn)(xe, jnp.asarray(wi), jnp.asarray(wo))  # [E, C, H]
        ye = shard_activation(ye, _auto_spec(EXPERT_AXIS, None, None))

        y = jnp.einsum(
            "ech,nec->nh", ye, combine.astype(self.dtype),
            preferred_element_type=self.dtype,
        )
        y = shard_activation(y, _auto_spec(BATCH_AXES, None))
        return y.reshape(*lead, H).astype(self.dtype), aux.astype(jnp.float32)
