"""Expert-parallel Mixture-of-Experts layer over the ``ep`` mesh axis.

The reference has NO MoE/expert parallelism anywhere (SURVEY §2.10: "EP —
Absent"); the mesh here carries a first-class ``ep`` axis (a sub-axis of data
parallelism, ``parallel/mesh.py``), and this module makes it real — beyond-
parity capability, like ring-attention CP.

TPU-native formulation: the GShard/Switch dense-dispatch pattern —
routing becomes two einsums against a one-hot dispatch tensor, so the
all-to-alls are GSPMD-inserted reshards between the token-sharded and
expert-sharded layouts instead of hand-written ``all_to_all`` calls, and
everything stays static-shaped (capacity-bounded) for jit:

1. router probs ``[N, E]`` (fp32 softmax);
2. top-k choice per token, position-in-expert by cumulative sum, tokens
   beyond ``capacity`` dropped (their combine weight is zero — standard
   capacity-factor semantics);
3. ``dispatch [N, E, C]`` one-hot and ``combine = dispatch * gate``;
4. ``xe = einsum('nh,nec->ech', x, dispatch)`` — result sharded ``e→ep``
   (the "all-to-all" to expert-major layout);
5. per-expert fused gate-up/down FFN, vmapped over local experts, inner
   dims TP-sharded exactly like the dense MLP;
6. ``y = einsum('ech,nec->nh', ye, combine)`` — back to token-major.

The load-balancing auxiliary loss is the Switch-Transformer form
``E * sum_e(frac_tokens_e * mean_prob_e)`` (=1 at perfect balance).
"""

from __future__ import annotations

from typing import Any, Callable, Tuple

import jax
import jax.numpy as jnp
from flax import linen as nn

from neuronx_distributed_tpu.parallel.layers import shard_activation
from neuronx_distributed_tpu.parallel.mesh import (
    BATCH_AXES,
    EXPERT_AXIS,
    TENSOR_AXES,
    ambient_manual_axes,
    strip_axes_from_spec,
)
from jax.sharding import PartitionSpec as P


def _auto_spec(*entries) -> P:
    """PartitionSpec with any ambient-*manual* mesh axes removed.

    Inside the 1F1B engine's partial-manual shard_map (manual ``dp/ep/pp``)
    GSPMD sharding constraints may only reference the remaining auto axes;
    a manual axis in a constraint is an error.  Dropping it is also the
    semantically right thing: under the engine the batch is already split
    per (dp, ep) rank, so ``ep`` degenerates to pure data parallelism and
    expert weights are simply replicated within the stage."""
    return strip_axes_from_spec(P(*entries), ambient_manual_axes())

Dtype = Any
Initializer = Callable[..., jax.Array]


def load_balancing_loss(probs: jax.Array, expert_mask: jax.Array) -> jax.Array:
    """Switch aux loss: ``E * sum_e(fraction_routed_e * mean_router_prob_e)``.
    ``probs [N, E]`` fp32 router probabilities, ``expert_mask [N, E]`` 0/1
    top-k selections (pre-capacity)."""
    E = probs.shape[-1]
    frac = jnp.mean(expert_mask.astype(jnp.float32), axis=0)
    mean_p = jnp.mean(probs, axis=0)
    return E * jnp.sum(frac * mean_p)


class ExpertParallelMLP(nn.Module):
    """Top-k routed MoE FFN; experts sharded over ``ep``, each expert's
    hidden dim over the TP axes (the dense MLP's sharding, per expert).

    Input/output ``[..., hidden]``; returns ``(y, aux_loss)``.
    """

    num_experts: int
    intermediate_size: int
    top_k: int = 2
    capacity_factor: float = 1.25
    # "einsum": GShard dense one-hot dispatch/combine [N, E, C] tensors —
    #   collective-friendly and the parity oracle, but O(N·E·C) memory
    #   (multi-GB at Mixtral scale: N≈32k, E=8, C≈6k — VERDICT r3 weak #3).
    # "scatter": capacity-bucketed segment-sum dispatch + gather combine —
    #   O(N·K·H + E·C·H) memory, the trainable path at preset scale.
    dispatch: str = "einsum"
    # manual expert parallelism (inside the PP engine's shard_map, where
    # ``ep`` is a manual axis): ``num_experts`` is then the LOCAL expert
    # count held by this ep rank and ``num_experts_global`` the routing
    # space.  Tokens are all-gathered over ep, each rank computes its
    # experts' contributions, and a psum_scatter returns each rank its
    # token shard — the explicit form of the a2a GSPMD inserts on the
    # pp==1 path.  0 = single-program GSPMD mode (num_experts is global).
    num_experts_global: int = 0
    # "topk": tokens choose experts (GShard/Switch/Mixtral; needs the aux
    #   loss + capacity drops).  "expert_choice": experts choose their top-C
    #   tokens (Zhou et al. 2022, C = ceil(factor*k*N/E)) — every expert is
    #   exactly full (no aux pressure; aux returns 0), though a token picked
    #   by NO expert passes through residual-only, and ``top_k`` only sets
    #   the AVERAGE experts per token.  CAUTION for causal LMs: each
    #   expert's top-C compares a token's score against LATER tokens of the
    #   same batch, so routing leaks future information during training and
    #   differs between teacher-forced training and incremental decoding —
    #   expert choice is principally an encoder/non-autoregressive router.
    router_type: str = "topk"
    dtype: Dtype = jnp.bfloat16
    param_dtype: Dtype = jnp.float32
    kernel_init: Initializer = nn.initializers.lecun_normal()

    @nn.compact
    def __call__(self, x: jax.Array) -> Tuple[jax.Array, jax.Array]:
        from jax import lax

        manual_ep = bool(self.num_experts_global) and \
            self.num_experts_global != self.num_experts
        Eg = self.num_experts_global or self.num_experts
        if manual_ep and EXPERT_AXIS not in ambient_manual_axes():
            raise ValueError(
                "num_experts_global != num_experts requires a manual ep axis "
                "(the PP engine's shard_map); under plain GSPMD pass the "
                "global count as num_experts"
            )
        if self.top_k > Eg:
            raise ValueError(f"top_k={self.top_k} > num_experts={Eg}")
        if self.dispatch not in ("einsum", "scatter"):
            raise ValueError(
                f"unknown dispatch {self.dispatch!r} (einsum | scatter)")
        if self.router_type not in ("topk", "expert_choice"):
            raise ValueError(
                f"unknown router_type {self.router_type!r} "
                "(topk | expert_choice)")
        *lead, H = x.shape
        E, I, K = self.num_experts, self.intermediate_size, self.top_k
        xt = x.reshape(-1, H)
        if manual_ep:
            # gather every ep rank's token shard; conjugate psum_scatter
            # below returns this rank's shard of the combined output
            xt = lax.all_gather(xt, EXPERT_AXIS, axis=0, tiled=True)
        N = xt.shape[0]
        # static capacity: ceil(K * N / Eg * factor), at least K, multiple of 4
        cap = max(int(self.capacity_factor * K * N / Eg + 0.999), K)
        cap = min(-(-cap // 4) * 4, N)

        router = self.param(
            "router", nn.with_partitioning(self.kernel_init, (None, None)),
            (H, Eg), self.param_dtype,
        )
        wi = self.param(
            "gate_up",
            nn.with_partitioning(self.kernel_init, (EXPERT_AXIS, None, None, TENSOR_AXES)),
            (E, H, 2, I), self.param_dtype,
        )
        wo = self.param(
            "down",
            nn.with_partitioning(self.kernel_init, (EXPERT_AXIS, TENSOR_AXES, None)),
            (E, I, H), self.param_dtype,
        )

        # -- routing (fp32), over the GLOBAL expert space ---------------------
        logits = jnp.einsum(
            "nh,he->ne", xt.astype(jnp.float32), router.astype(jnp.float32)
        )
        probs = jax.nn.softmax(logits, axis=-1)  # [N, Eg]

        def ffn(x_e, wi_e, wo_e):
            gu = jnp.einsum("ch,hfi->cfi", x_e, wi_e.astype(self.dtype),
                            preferred_element_type=self.dtype)
            h = jax.nn.silu(gu[:, 0, :]) * gu[:, 1, :]
            h = shard_activation(h, _auto_spec(None, TENSOR_AXES))
            return jnp.einsum("ci,ih->ch", h, wo_e.astype(self.dtype),
                              preferred_element_type=self.dtype)

        if self.router_type == "expert_choice":
            # experts choose their top-C tokens (Zhou et al. 2022): every
            # expert processes exactly C = cap tokens — perfect balance, no
            # aux pressure (a token chosen by no expert is residual-only;
            # see the router_type docstring for the causal-LM caveat).
            # Gather/scatter dispatch is inherent (``dispatch`` is moot).
            e0 = lax.axis_index(EXPERT_AXIS) * E if manual_ep else 0
            w_all = probs.T.astype(jnp.float32)  # [Eg, N]
            w_loc = lax.dynamic_slice_in_dim(w_all, e0, E, axis=0) \
                if manual_ep else w_all
            g_ec, tok_idx = jax.lax.top_k(w_loc, cap)  # [E, C]
            xe = xt.astype(self.dtype)[tok_idx.reshape(-1)].reshape(E, cap, H)
            xe = shard_activation(xe, _auto_spec(EXPERT_AXIS, None, None))
            ye = jax.vmap(ffn)(xe, jnp.asarray(wi), jnp.asarray(wo))  # [E, C, H]
            ye = shard_activation(ye, _auto_spec(EXPERT_AXIS, None, None))
            contrib = (g_ec.astype(ye.dtype)[..., None] * ye).reshape(E * cap, H)
            y = jax.ops.segment_sum(contrib, tok_idx.reshape(-1), num_segments=N)
            if manual_ep:
                y = lax.psum_scatter(y, EXPERT_AXIS, scatter_dimension=0,
                                     tiled=True)
            y = shard_activation(y, _auto_spec(BATCH_AXES, None))
            return (y.reshape(*lead, H).astype(self.dtype),
                    jnp.zeros((), jnp.float32))

        gate_vals, expert_idx = jax.lax.top_k(probs, K)  # [N, K]
        onehot = jax.nn.one_hot(expert_idx, Eg, dtype=jnp.float32)  # [N, K, Eg]
        expert_mask = jnp.max(onehot, axis=1)  # [N, Eg] (for the aux loss)
        aux = load_balancing_loss(probs, expert_mask)

        # position of each (token, choice) within its expert's buffer:
        # cumulative count over tokens, k-th choices ranked after (k-1)-th
        # (the GShard priority convention)
        flat = onehot.transpose(1, 0, 2).reshape(K * N, Eg)  # k-major
        pos_flat = jnp.cumsum(flat, axis=0) - flat  # [K*N, Eg]
        pos = pos_flat.reshape(K, N, Eg).transpose(1, 0, 2)  # [N, K, Eg]
        pos_in_expert = jnp.sum(pos * onehot, axis=-1)  # [N, K]
        keep = pos_in_expert < cap  # capacity drop
        gate_vals = gate_vals * keep

        # normalize kept gates per token (Mixtral convention); fp32
        denom = jnp.maximum(jnp.sum(gate_vals, axis=-1, keepdims=True), 1e-9)
        gate_vals = gate_vals / denom

        # under manual ep this rank computes experts [e0, e0+E) of the
        # global space; elsewhere e0 = 0 and E == Eg
        e0 = lax.axis_index(EXPERT_AXIS) * E if manual_ep else 0

        if self.dispatch == "scatter":
            # flat capacity slot per (token, choice) among THIS rank's
            # experts; dropped or remote tokens target the sentinel row
            # E*cap, which never feeds an expert
            local_idx = expert_idx - e0
            mine = keep & (local_idx >= 0) & (local_idx < E)
            slot = jnp.where(
                mine, local_idx * cap + pos_in_expert.astype(jnp.int32), E * cap
            )  # [N, K] int
            src = jnp.broadcast_to(
                xt.astype(self.dtype)[:, None, :], (N, K, H)).reshape(N * K, H)
            xe_flat = jax.ops.segment_sum(
                src, slot.reshape(-1), num_segments=E * cap + 1
            )  # a slot holds at most one token, so "sum" is a placement
            xe = xe_flat[: E * cap].reshape(E, cap, H).astype(self.dtype)
            xe = shard_activation(xe, _auto_spec(EXPERT_AXIS, None, None))

            ye = jax.vmap(ffn)(xe, jnp.asarray(wi), jnp.asarray(wo))  # [E, C, H]
            ye = shard_activation(ye, _auto_spec(EXPERT_AXIS, None, None))
            ye_flat = jnp.concatenate(
                [ye.reshape(E * cap, H), jnp.zeros((1, H), ye.dtype)])
            y_nk = ye_flat[slot.reshape(-1)].reshape(N, K, H)  # sentinel -> zeros
            y = jnp.einsum(
                "nkh,nk->nh", y_nk, gate_vals.astype(ye.dtype),
                preferred_element_type=self.dtype,
            )
        else:
            # dispatch [N, Eg, C] / combine [N, Eg, C]
            pos_oh = jax.nn.one_hot(
                jnp.where(keep, pos_in_expert, cap).astype(jnp.int32), cap,
                dtype=jnp.float32,
            )  # [N, K, C] (dropped -> all-zero row)
            dispatch = jnp.einsum("nke,nkc->nec", onehot, pos_oh)
            combine = jnp.einsum("nke,nkc,nk->nec", onehot, pos_oh, gate_vals)
            if manual_ep:  # this rank's expert columns only
                dispatch = lax.dynamic_slice_in_dim(dispatch, e0, E, axis=1)
                combine = lax.dynamic_slice_in_dim(combine, e0, E, axis=1)

            xe = jnp.einsum(
                "nh,nec->ech", xt.astype(self.dtype), dispatch.astype(self.dtype),
                preferred_element_type=self.dtype,
            )
            # expert-major layout: experts over ep, tokens replicated within
            xe = shard_activation(xe, _auto_spec(EXPERT_AXIS, None, None))

            ye = jax.vmap(ffn)(xe, jnp.asarray(wi), jnp.asarray(wo))  # [E, C, H]
            ye = shard_activation(ye, _auto_spec(EXPERT_AXIS, None, None))

            y = jnp.einsum(
                "ech,nec->nh", ye, combine.astype(self.dtype),
                preferred_element_type=self.dtype,
            )
        if manual_ep:
            # every rank holds partial sums for ALL tokens (its experts'
            # contributions); the conjugate of the entry all_gather returns
            # each rank its token shard, fully combined
            y = lax.psum_scatter(y, EXPERT_AXIS, scatter_dimension=0, tiled=True)
        y = shard_activation(y, _auto_spec(BATCH_AXES, None))
        return y.reshape(*lead, H).astype(self.dtype), aux.astype(jnp.float32)
