"""Parallelism primitives: mesh state, collective mappings, TP layers, loss,
GQA QKV, norms.

Mirrors the reference's ``parallel_layers`` package surface
(``src/neuronx_distributed/parallel_layers/__init__.py:4-22``)."""

from neuronx_distributed_tpu.parallel import mappings
from neuronx_distributed_tpu.parallel.layers import (
    ColumnParallelLinear,
    ParallelEmbedding,
    RowParallelLinear,
    shard_activation,
    trailing_spec,
)
from neuronx_distributed_tpu.parallel.loss import (
    parallel_cross_entropy,
    vocab_parallel_cross_entropy,
)
from neuronx_distributed_tpu.parallel.mesh import (
    BATCH_AXES,
    CONTEXT_AXIS,
    DATA_AXIS,
    EXPERT_AXIS,
    KV_REPLICA_AXIS,
    MESH_AXES,
    PIPELINE_AXIS,
    SEQUENCE_AXES,
    TENSOR_AXES,
    TENSOR_AXIS,
    MeshConfig,
    destroy_model_parallel,
    get_data_parallel_size,
    get_kv_size_multiplier,
    get_mesh,
    get_pipeline_parallel_size,
    get_tensor_parallel_size,
    initialize_model_parallel,
    mesh_context,
    model_parallel_is_initialized,
    named_sharding,
)
from neuronx_distributed_tpu.parallel.moe import (
    ExpertParallelMLP,
    load_balancing_loss,
)
from neuronx_distributed_tpu.parallel.norm import LayerNorm, RMSNorm
from neuronx_distributed_tpu.parallel.pad import (
    pad_axis_to,
    pad_llama_params,
    pad_to_multiple,
)
from neuronx_distributed_tpu.parallel.qkv import (
    GQAQKVColumnParallelLinear,
    KV_HEAD_AXES,
    Q_HEAD_AXES,
)

__all__ = [
    "BATCH_AXES",
    "CONTEXT_AXIS",
    "DATA_AXIS",
    "EXPERT_AXIS",
    "KV_REPLICA_AXIS",
    "MESH_AXES",
    "PIPELINE_AXIS",
    "SEQUENCE_AXES",
    "TENSOR_AXES",
    "TENSOR_AXIS",
    "ExpertParallelMLP",
    "load_balancing_loss",
    "Q_HEAD_AXES",
    "KV_HEAD_AXES",
    "MeshConfig",
    "initialize_model_parallel",
    "destroy_model_parallel",
    "model_parallel_is_initialized",
    "get_mesh",
    "get_tensor_parallel_size",
    "get_pipeline_parallel_size",
    "get_data_parallel_size",
    "get_kv_size_multiplier",
    "mesh_context",
    "named_sharding",
    "ColumnParallelLinear",
    "RowParallelLinear",
    "ParallelEmbedding",
    "GQAQKVColumnParallelLinear",
    "shard_activation",
    "trailing_spec",
    "parallel_cross_entropy",
    "vocab_parallel_cross_entropy",
    "LayerNorm",
    "RMSNorm",
    "pad_axis_to",
    "pad_llama_params",
    "pad_to_multiple",
    "mappings",
]
