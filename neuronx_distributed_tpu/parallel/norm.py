"""Normalization layers (reference ``parallel_layers/layer_norm.py`` and the
RMSNorm in ``modeling_llama_nxd.py:80-95``).

Computation runs in fp32 regardless of input dtype — the explicit-dtype
replacement for the reference's ``XLA_DOWNCAST_BF16`` double-trick
(``modeling_llama_nxd.py:125``).  In SP regions the input is sequence-sharded
and the op is purely elementwise over the hidden dim, so no collective is
needed; weight gradients are psum'd across TP by autodiff/GSPMD — the
reference needs a separate ``allreduce_sequence_parallel_gradients`` pass
(``grads.py:249-264``) only because its LN weights live outside autograd's
view of the TP group."""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
from flax import linen as nn


class RMSNorm(nn.Module):
    eps: float = 1e-6
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        weight = self.param("weight", nn.initializers.ones_init(), (x.shape[-1],), self.param_dtype)
        xf = x.astype(jnp.float32)
        var = jnp.mean(jnp.square(xf), axis=-1, keepdims=True)
        y = xf * jax.lax.rsqrt(var + self.eps)
        return (y * weight.astype(jnp.float32)).astype(self.dtype)


class LayerNorm(nn.Module):
    eps: float = 1e-5
    use_bias: bool = True
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        dim = x.shape[-1]
        weight = self.param("weight", nn.initializers.ones_init(), (dim,), self.param_dtype)
        xf = x.astype(jnp.float32)
        mean = jnp.mean(xf, axis=-1, keepdims=True)
        var = jnp.var(xf, axis=-1, keepdims=True)
        y = (xf - mean) * jax.lax.rsqrt(var + self.eps)
        y = y * weight.astype(jnp.float32)
        if self.use_bias:
            bias = self.param("bias", nn.initializers.zeros_init(), (dim,), self.param_dtype)
            y = y + bias.astype(jnp.float32)
        return y.astype(self.dtype)
