"""Conjugate-pair collective mappings (explicit shard_map path).

TPU-native counterpart of the reference's autograd-aware collectives
(``parallel_layers/mappings.py:126-283``): the 7 Megatron conjugate pairs,
here as ``jax.custom_vjp`` functions over named mesh axes, usable inside
``shard_map``.  The production layers (``parallel/layers.py``) rely on GSPMD
sharding constraints instead — XLA inserts these same collectives
automatically — but the explicit forms are needed where collective placement
must be exact (vocab-parallel loss, parity tests, ring attention).

Forward/backward conjugacy table (reference ``mappings.py``):

=============================================  ==========================
forward                                        backward
=============================================  ==========================
copy (identity)                                psum over tp
psum over tp                                   copy (identity)
split along last dim                           all-gather along last dim
all-gather along last dim                      split along last dim
split along seq (first data) dim               all-gather along seq dim
all-gather along seq dim                       reduce-scatter | split
reduce-scatter along seq dim                   all-gather along seq dim
=============================================  ==========================
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple, Union

import jax
import jax.numpy as jnp
from jax import lax

from neuronx_distributed_tpu.parallel.mesh import TENSOR_AXES, manual_axis_size

AxisNames = Union[str, Tuple[str, ...]]


def resolve_axes(axis_name: Optional[AxisNames]) -> AxisNames:
    """Default an axis-name argument to the full TP axis tuple."""
    return TENSOR_AXES if axis_name is None else axis_name


# internal alias used throughout this module
_axes = resolve_axes


def axis_size(axis_name: Optional[AxisNames] = None) -> int:
    """Product of the given (possibly tuple) axis sizes. Trace-time constant."""
    ax = _axes(axis_name)
    if isinstance(ax, str):
        ax = (ax,)
    size = 1
    for a in ax:
        size *= manual_axis_size(a)
    return size


def axis_rank(axis_name: Optional[AxisNames] = None) -> jax.Array:
    """Combined rank along (possibly tuple) axes, major-to-minor order."""
    ax = _axes(axis_name)
    if isinstance(ax, str):
        ax = (ax,)
    rank = jnp.zeros((), dtype=jnp.int32)
    for a in ax:
        rank = rank * manual_axis_size(a) + lax.axis_index(a)
    return rank


def _split_along_dim(x: jax.Array, dim: int, axis_name: AxisNames) -> jax.Array:
    n = axis_size(axis_name)
    if n == 1:
        return x
    if x.shape[dim] % n != 0:
        raise ValueError(
            f"cannot split dim {dim} of size {x.shape[dim]} across {n} ranks "
            f"(axis {axis_name}): not divisible"
        )
    rank = axis_rank(axis_name)
    chunk = x.shape[dim] // n
    return lax.dynamic_slice_in_dim(x, rank * chunk, chunk, axis=dim)


# ---------------------------------------------------------------------------
# copy <-> psum   (reference _CopyToModelParallelRegion / _ReduceFrom...)
# ---------------------------------------------------------------------------


@functools.partial(jax.custom_vjp, nondiff_argnums=(1,))
def copy_to_tensor_parallel_region(x: jax.Array, axis_name: Optional[AxisNames] = None) -> jax.Array:
    """fwd identity, bwd psum over the TP axes (``mappings.py:126-141``)."""
    return x


def _copy_fwd(x, axis_name):
    return x, None


def _copy_bwd(axis_name, _, g):
    return (lax.psum(g, _axes(axis_name)),)


copy_to_tensor_parallel_region.defvjp(_copy_fwd, _copy_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(1,))
def reduce_from_tensor_parallel_region(x: jax.Array, axis_name: Optional[AxisNames] = None) -> jax.Array:
    """fwd psum over TP, bwd identity (``mappings.py:144-159``)."""
    return lax.psum(x, _axes(axis_name))


def _reduce_fwd(x, axis_name):
    return lax.psum(x, _axes(axis_name)), None


def _reduce_bwd(axis_name, _, g):
    return (g,)


reduce_from_tensor_parallel_region.defvjp(_reduce_fwd, _reduce_bwd)


# ---------------------------------------------------------------------------
# split/gather along the LAST dim (TP region; reference _ScatterTo/_GatherFrom)
# ---------------------------------------------------------------------------


@functools.partial(jax.custom_vjp, nondiff_argnums=(1,))
def scatter_to_tensor_parallel_region(x: jax.Array, axis_name: Optional[AxisNames] = None) -> jax.Array:
    """fwd split last dim, bwd all-gather last dim (``mappings.py:162-177``)."""
    return _split_along_dim(x, -1, _axes(axis_name))


def _scatter_tp_fwd(x, axis_name):
    return _split_along_dim(x, -1, _axes(axis_name)), None


def _scatter_tp_bwd(axis_name, _, g):
    return (lax.all_gather(g, _axes(axis_name), axis=g.ndim - 1, tiled=True),)


scatter_to_tensor_parallel_region.defvjp(_scatter_tp_fwd, _scatter_tp_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(1,))
def gather_from_tensor_parallel_region(x: jax.Array, axis_name: Optional[AxisNames] = None) -> jax.Array:
    """fwd all-gather last dim, bwd split last dim (``mappings.py:180-195``)."""
    return lax.all_gather(x, _axes(axis_name), axis=x.ndim - 1, tiled=True)


def _gather_tp_fwd(x, axis_name):
    return lax.all_gather(x, _axes(axis_name), axis=x.ndim - 1, tiled=True), None


def _gather_tp_bwd(axis_name, _, g):
    return (_split_along_dim(g, -1, _axes(axis_name)),)


gather_from_tensor_parallel_region.defvjp(_gather_tp_fwd, _gather_tp_bwd)


# ---------------------------------------------------------------------------
# sequence-parallel region: first ("sequence") dim, configurable
# (reference _ScatterToSequenceParallelRegion etc., mappings.py:198-250)
# ---------------------------------------------------------------------------


@functools.partial(jax.custom_vjp, nondiff_argnums=(1, 2))
def scatter_to_sequence_parallel_region(
    x: jax.Array, seq_dim: int = 0, axis_name: Optional[AxisNames] = None
) -> jax.Array:
    """fwd split seq dim, bwd all-gather seq dim (``mappings.py:198-210``)."""
    return _split_along_dim(x, seq_dim, _axes(axis_name))


def _scatter_sp_fwd(x, seq_dim, axis_name):
    return _split_along_dim(x, seq_dim, _axes(axis_name)), None


def _scatter_sp_bwd(seq_dim, axis_name, _, g):
    return (lax.all_gather(g, _axes(axis_name), axis=seq_dim, tiled=True),)


scatter_to_sequence_parallel_region.defvjp(_scatter_sp_fwd, _scatter_sp_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(1, 2, 3))
def gather_from_sequence_parallel_region(
    x: jax.Array,
    seq_dim: int = 0,
    to_tensor_parallel: bool = True,
    axis_name: Optional[AxisNames] = None,
) -> jax.Array:
    """fwd all-gather seq dim; bwd reduce-scatter (if feeding a TP block) or
    plain split (``mappings.py:213-232``)."""
    return lax.all_gather(x, _axes(axis_name), axis=seq_dim, tiled=True)


def _gather_sp_fwd(x, seq_dim, to_tensor_parallel, axis_name):
    return lax.all_gather(x, _axes(axis_name), axis=seq_dim, tiled=True), None


def _gather_sp_bwd(seq_dim, to_tensor_parallel, axis_name, _, g):
    ax = _axes(axis_name)
    if to_tensor_parallel:
        return (lax.psum_scatter(g, ax, scatter_dimension=seq_dim, tiled=True),)
    return (_split_along_dim(g, seq_dim, ax),)


gather_from_sequence_parallel_region.defvjp(_gather_sp_fwd, _gather_sp_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(1, 2))
def reduce_scatter_to_sequence_parallel_region(
    x: jax.Array, seq_dim: int = 0, axis_name: Optional[AxisNames] = None
) -> jax.Array:
    """fwd reduce-scatter seq dim, bwd all-gather seq dim (``mappings.py:235-250``)."""
    return lax.psum_scatter(x, _axes(axis_name), scatter_dimension=seq_dim, tiled=True)


def _rs_sp_fwd(x, seq_dim, axis_name):
    return lax.psum_scatter(x, _axes(axis_name), scatter_dimension=seq_dim, tiled=True), None


def _rs_sp_bwd(seq_dim, axis_name, _, g):
    return (lax.all_gather(g, _axes(axis_name), axis=seq_dim, tiled=True),)


reduce_scatter_to_sequence_parallel_region.defvjp(_rs_sp_fwd, _rs_sp_bwd)
