"""Tensor-parallel layers (GSPMD production path).

TPU-native re-design of the reference's Megatron TP modules
(``parallel_layers/layers.py``: ``ColumnParallelLinear`` :372-516,
``RowParallelLinear`` :519-660, ``ParallelEmbedding`` :97-205).  Instead of
hand-written autograd Functions with explicit all-gather / all-reduce /
reduce-scatter calls (``layers.py:208-334``), each module:

- creates its kernel with a :class:`flax.linen.Partitioned` metadata spec
  (column-parallel → sharded on the output dim, row-parallel → input dim,
  embedding → vocab dim), and
- constrains its activations with ``with_sharding_constraint`` so GSPMD
  inserts exactly the Megatron collectives — including the backward-pass
  conjugates and the async overlap the reference implements by hand
  (``layers.py:270-305``), which XLA's latency-hiding scheduler recovers
  automatically.

Sequence parallelism (Megatron-SP, reference ``mappings.py:198-250`` +
``layers.py:230-238,311-324``) is an activation-sharding choice here: SP
regions carry activations as ``[batch, seq/TP, hidden]``; entering a column-
parallel layer XLA all-gathers the sequence dim, and a row-parallel layer's
output constraint reduce-scatters back onto it.

Fused projections (reference ``stride=`` for QKV / gate-up,
``layers.py:372-516``, ``modeling_llama_nxd.py:142-150``) are expressed
shape-wise: ``n_fused > 1`` keeps a leading fused axis on the kernel so every
TP shard holds matching slices of each fused part — no interleaving tricks.
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp
from flax import linen as nn
from jax.sharding import NamedSharding, PartitionSpec as P

from neuronx_distributed_tpu.parallel.mesh import (
    SEQUENCE_AXES,
    TENSOR_AXES,
    get_mesh,
    model_parallel_is_initialized,
)
from neuronx_distributed_tpu.utils.common import divide

Dtype = Any
Initializer = Callable[..., jax.Array]

_U = P.UNCONSTRAINED


def shard_activation(x: jax.Array, spec: P) -> jax.Array:
    """Constrain ``x``'s sharding over the global mesh (no-op if no mesh).

    Inside a partial-manual ``shard_map`` region (the pipeline engine makes
    ``pp`` manual) the constraint must be expressed against the *abstract*
    context mesh — a NamedSharding over the concrete mesh carries all-Auto
    axis types and is rejected by jax 0.9's canonicalization when any axis
    is Manual in context.  On older jax (< 0.5) there is no abstract-mesh
    tracking; the concrete-mesh constraint is the classic behavior."""
    if not model_parallel_is_initialized():
        return x
    get_abstract = getattr(jax.sharding, "get_abstract_mesh", None)
    if get_abstract is not None:
        abstract = get_abstract()
        if abstract.axis_names:  # inside jit/shard_map: use the context mesh
            return jax.lax.with_sharding_constraint(
                x, NamedSharding(abstract, spec))
    return jax.lax.with_sharding_constraint(x, NamedSharding(get_mesh(), spec))


def trailing_spec(ndim: int, **dims: Any) -> P:
    """Build a PartitionSpec that pins only dims addressed from the end.

    ``trailing_spec(3, last=TENSOR_AXES)`` → P(U, U, ('kvr','tp')).
    Keys: ``last`` (features dim), ``seq`` (dim -2).
    """
    entries = [_U] * ndim
    if "last" in dims:
        entries[-1] = dims["last"]
    if "seq" in dims and ndim >= 2:
        entries[-2] = dims["seq"]
    return P(*entries)


class ColumnParallelLinear(nn.Module):
    """Linear with output-dim sharding (reference ``layers.py:372-516``).

    Args:
      features: global output size (sum over TP shards).
      n_fused: number of fused sub-projections (QKV=3, gate-up=2).  When >1
        the kernel carries an explicit fused axis and the output is returned
        as ``[..., n_fused, features // n_fused]`` so each TP shard holds
        matching slices of every part (TPU-native form of reference
        ``stride=``).
      gather_output: all-gather the output so every shard sees the full
        feature dim (reference ``gather_output=True``).
      sequence_parallel: input activations are sequence-sharded
        ``[batch, seq/TP, hidden]``; XLA all-gathers seq before the matmul.
    """

    features: int
    use_bias: bool = True
    gather_output: bool = False
    sequence_parallel: bool = False
    n_fused: int = 1
    # LoRA (low-rank adaptation): rank > 0 adds a frozen-base-friendly
    # ``y += (alpha/r) * (x @ A) @ B`` path.  A ``[in, r]`` is replicated,
    # B follows the kernel's output sharding and starts at ZERO (the adapter
    # begins as the identity).  Freeze the base with
    # ``peft.lora_trainable`` + ``initialize_parallel_optimizer(trainable=)``.
    lora_rank: int = 0
    lora_alpha: float = 16.0
    dtype: Dtype = jnp.bfloat16
    param_dtype: Dtype = jnp.float32
    kernel_init: Initializer = nn.initializers.lecun_normal()
    bias_init: Initializer = nn.initializers.zeros_init()

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        in_features = x.shape[-1]
        per_fused = divide(self.features, self.n_fused)

        if self.n_fused == 1:
            kernel = self.param(
                "kernel",
                nn.with_partitioning(self.kernel_init, (None, TENSOR_AXES)),
                (in_features, self.features),
                self.param_dtype,
            )
        else:
            kernel = self.param(
                "kernel",
                nn.with_partitioning(self.kernel_init, (None, None, TENSOR_AXES)),
                (in_features, self.n_fused, per_fused),
                self.param_dtype,
            )

        x = x.astype(self.dtype)
        if self.sequence_parallel:
            x = shard_activation(x, trailing_spec(x.ndim, seq=SEQUENCE_AXES, last=None))
        kernel = jnp.asarray(kernel, self.dtype)

        if self.n_fused == 1:
            y = jax.lax.dot_general(
                x, kernel, (((x.ndim - 1,), (0,)), ((), ())), preferred_element_type=self.dtype
            )
        else:
            y = jnp.einsum("...h,hfp->...fp", x, kernel, preferred_element_type=self.dtype)
        # The load-bearing constraint: output sharded on the feature dim makes
        # GSPMD insert the Megatron collectives (and their bwd conjugates).
        y = shard_activation(y, trailing_spec(y.ndim, last=TENSOR_AXES))

        if self.lora_rank > 0:
            r = self.lora_rank
            a = self.param(
                "lora_a",
                nn.with_partitioning(nn.initializers.lecun_normal(), (None, None)),
                (in_features, r), self.param_dtype,
            )
            xa = jnp.einsum("...h,hr->...r", x, jnp.asarray(a, self.dtype),
                            preferred_element_type=self.dtype)
            if self.n_fused == 1:
                b = self.param(
                    "lora_b",
                    nn.with_partitioning(nn.initializers.zeros_init(), (None, TENSOR_AXES)),
                    (r, self.features), self.param_dtype,
                )
                delta = jnp.einsum("...r,rp->...p", xa, jnp.asarray(b, self.dtype),
                                   preferred_element_type=self.dtype)
            else:
                b = self.param(
                    "lora_b",
                    nn.with_partitioning(nn.initializers.zeros_init(),
                                         (None, None, TENSOR_AXES)),
                    (r, self.n_fused, per_fused), self.param_dtype,
                )
                delta = jnp.einsum("...r,rfp->...fp", xa, jnp.asarray(b, self.dtype),
                                   preferred_element_type=self.dtype)
            y = y + (self.lora_alpha / r) * delta

        if self.use_bias:
            if self.n_fused == 1:
                bias = self.param(
                    "bias",
                    nn.with_partitioning(self.bias_init, (TENSOR_AXES,)),
                    (self.features,),
                    self.param_dtype,
                )
            else:
                bias = self.param(
                    "bias",
                    nn.with_partitioning(self.bias_init, (None, TENSOR_AXES)),
                    (self.n_fused, per_fused),
                    self.param_dtype,
                )
            y = y + jnp.asarray(bias, self.dtype)

        if self.gather_output:
            y = shard_activation(y, trailing_spec(y.ndim, last=None))
        return y


class RowParallelLinear(nn.Module):
    """Linear with input-dim sharding (reference ``layers.py:519-660``).

    The matmul contracts over the sharded input dim, so each shard produces a
    partial sum; the output constraint makes GSPMD finish it with an
    all-reduce (``input_is_parallel`` + dense output, reference
    ``layers.py:654-658``) or a reduce-scatter onto the sequence dim
    (``sequence_parallel``)."""

    features: int
    use_bias: bool = True
    input_is_parallel: bool = True
    sequence_parallel: bool = False
    # Sub-axis order of the sharded input dim.  Attention outputs arrive in
    # q-head order — sharded ('tp','kvr') — so the o_proj sets this to match
    # and no resharding happens between attention and projection.
    input_partition_axes: tuple = TENSOR_AXES
    # LoRA: A follows the kernel's input sharding (the x @ A contraction gets
    # the same psum as the base matmul), B is replicated and starts at zero.
    lora_rank: int = 0
    lora_alpha: float = 16.0
    dtype: Dtype = jnp.bfloat16
    param_dtype: Dtype = jnp.float32
    kernel_init: Initializer = nn.initializers.lecun_normal()
    bias_init: Initializer = nn.initializers.zeros_init()

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        in_features = x.shape[-1]
        kernel = self.param(
            "kernel",
            nn.with_partitioning(self.kernel_init, (self.input_partition_axes, None)),
            (in_features, self.features),
            self.param_dtype,
        )
        x = x.astype(self.dtype)
        if self.input_is_parallel:
            x = shard_activation(x, trailing_spec(x.ndim, last=self.input_partition_axes))
        y = jax.lax.dot_general(
            x,
            jnp.asarray(kernel, self.dtype),
            (((x.ndim - 1,), (0,)), ((), ())),
            preferred_element_type=self.dtype,
        )
        if self.sequence_parallel:
            y = shard_activation(y, trailing_spec(y.ndim, seq=SEQUENCE_AXES, last=None))
        else:
            y = shard_activation(y, trailing_spec(y.ndim, last=None))
        if self.lora_rank > 0:
            r = self.lora_rank
            a = self.param(
                "lora_a",
                nn.with_partitioning(nn.initializers.lecun_normal(),
                                     (self.input_partition_axes, None)),
                (in_features, r), self.param_dtype,
            )
            xa = jnp.einsum("...h,hr->...r", x, jnp.asarray(a, self.dtype),
                            preferred_element_type=self.dtype)
            # the contraction runs over the sharded dim: replicating the
            # result makes GSPMD finish the partial sums (same psum as y's)
            xa = shard_activation(xa, trailing_spec(xa.ndim, last=None))
            b = self.param(
                "lora_b",
                nn.with_partitioning(nn.initializers.zeros_init(), (None, None)),
                (r, self.features), self.param_dtype,
            )
            delta = jnp.einsum("...r,rp->...p", xa, jnp.asarray(b, self.dtype),
                               preferred_element_type=self.dtype)
            y = y + (self.lora_alpha / r) * delta
        if self.use_bias:
            # Bias is replicated and added after the reduction (reference adds
            # bias post all-reduce on the full output, layers.py:650-659).
            bias = self.param("bias", self.bias_init, (self.features,), self.param_dtype)
            y = y + jnp.asarray(bias, self.dtype)
        return y


class ParallelEmbedding(nn.Module):
    """Vocab-sharded embedding (reference ``layers.py:97-205``).

    The table is sharded along the vocab dim; GSPMD lowers the sharded take
    to the same mask-local-lookup + psum the reference writes by hand
    (out-of-range mask + all-reduce combine, ``layers.py:182-205``)."""

    num_embeddings: int
    features: int
    sequence_parallel_output: bool = False
    dtype: Dtype = jnp.bfloat16
    param_dtype: Dtype = jnp.float32
    embedding_init: Initializer = nn.initializers.normal(stddev=0.02)

    def setup(self):
        # setup-style (not compact) so ``attend`` can reuse the table for
        # tied LM heads
        self.embedding = self.param(
            "embedding",
            nn.with_partitioning(self.embedding_init, (TENSOR_AXES, None)),
            (self.num_embeddings, self.features),
            self.param_dtype,
        )

    def __call__(self, ids: jax.Array) -> jax.Array:
        y = jnp.take(jnp.asarray(self.embedding, self.dtype), ids, axis=0)
        if self.sequence_parallel_output:
            # Model enters its first SP region right after the embedding
            # (reference scatter_to_sequence_parallel_region,
            # modeling_llama_nxd.py:530-532).
            y = shard_activation(y, trailing_spec(y.ndim, seq=SEQUENCE_AXES, last=None))
        else:
            y = shard_activation(y, trailing_spec(y.ndim, last=None))
        return y

    def attend(self, x: jax.Array) -> jax.Array:
        """Project hidden states onto the (tied) table: ``[..., H] →
        [..., V]`` with the vocab dim sharded — the tied-embedding LM head
        (the reference handles tying via shared-weight registration,
        ``pipeline/partition.py:225-250``; here it is literal param reuse)."""
        y = jnp.einsum(
            "...h,vh->...v", x.astype(self.dtype), jnp.asarray(self.embedding, self.dtype),
            preferred_element_type=self.dtype,
        )
        return shard_activation(y, trailing_spec(y.ndim, last=TENSOR_AXES))
