"""Attention-head padding so ``num_heads`` divides the TP degree.

Reference: ``parallel_layers/pad.py:7-103`` (``pad_model`` walks torch
modules, zero-padding QKV output dims and o-proj input dims to the padded
head count).  The functional form here transforms a params pytree: Q/K/V
kernels (head dims) gain zero slices, the attention output projection
(input-side head dim) gains zero rows — so the padded model's outputs are
bit-identical: padded q/k/v heads produce attention outputs that meet only
zero rows in the o-projection.

GQA note: q heads are kv-major (q head ``j*G + g`` reads kv head ``j``), so
padding must keep the group size ``G = num_heads / num_kv_heads`` constant —
kv heads pad from ``NKV`` to ``NKV'`` and q heads from ``NKV*G`` to
``NKV'*G``; appended (zero) q-head groups then pair exactly with the
appended (zero) kv heads and every real pairing is preserved.
"""

from __future__ import annotations

import re
from typing import Any, Optional

import jax
import jax.numpy as jnp

# reference ``get_number_of_extra_heads`` arithmetic (``pad.py:15-24``)
from neuronx_distributed_tpu.utils.common import pad_to_multiple  # noqa: F401


def pad_axis_to(x: jax.Array, axis: int, new_size: int) -> jax.Array:
    """Zero-pad ``x`` along ``axis`` up to ``new_size``."""
    old = x.shape[axis]
    if old == new_size:
        return x
    if old > new_size:
        raise ValueError(f"cannot pad axis {axis} from {old} down to {new_size}")
    pads = [(0, 0)] * x.ndim
    pads[axis] = (0, new_size - old)
    return jnp.pad(x, pads)


def pad_llama_params(
    params: Any,
    old_heads: int,
    new_heads: int,
    head_dim: int,
    old_kv_heads: Optional[int] = None,
    new_kv_heads: Optional[int] = None,
) -> Any:
    """Pad a Llama params tree from ``old_heads`` to ``new_heads`` q heads
    (MHA: kv counts default to the q counts).  The group size must stay
    constant: ``new_heads / new_kv_heads == old_heads / old_kv_heads`` —
    that is what keeps the padded model's function identical (see module
    docstring).  Run the result under a config with the padded counts."""
    old_kv = old_heads if old_kv_heads is None else old_kv_heads
    new_kv = new_heads if new_kv_heads is None else new_kv_heads
    if old_heads % old_kv or new_heads % new_kv:
        raise ValueError("q heads must be a multiple of kv heads")
    if old_heads // old_kv != new_heads // new_kv:
        raise ValueError(
            f"padding must preserve the q-per-kv group size: "
            f"{old_heads}/{old_kv} != {new_heads}/{new_kv}"
        )

    def _pad(path_key, leaf):
        if re.search(r"qkv/q_(kernel|bias)$", path_key):
            return pad_axis_to(leaf, leaf.ndim - 2, new_heads)
        if re.search(r"qkv/(k|v)_(kernel|bias)$", path_key):
            return pad_axis_to(leaf, leaf.ndim - 2, new_kv)
        if re.search(r"o_proj/kernel$", path_key):
            return pad_axis_to(leaf, 0, new_heads * head_dim)
        return leaf

    flat = jax.tree_util.tree_flatten_with_path(params)[0]
    treedef = jax.tree_util.tree_structure(params)
    out = []
    for path, leaf in flat:
        key = "/".join(getattr(p, "key", str(getattr(p, "idx", p))) for p in path)
        out.append(_pad(key, leaf))
    return jax.tree_util.tree_unflatten(treedef, out)
