"""Parameter-efficient fine-tuning (LoRA) utilities.

Capability beyond the reference (which has no PEFT story): the TP layers
(:class:`~.parallel.layers.ColumnParallelLinear`,
:class:`~.parallel.layers.RowParallelLinear`,
:class:`~.parallel.qkv.GQAQKVColumnParallelLinear`) grow ``lora_rank`` /
``lora_alpha`` knobs adding a zero-initialized low-rank delta
``y += (alpha/r) * (x @ A) @ B`` whose factors shard consistently with the
base kernels (B follows the kernel's output sharding, A the input's), so
LoRA composes with TP/SP/FSDP/ZeRO unchanged.  This module holds the pieces
around the layers:

- :func:`lora_trainable` — the ``trainable=`` predicate for
  ``initialize_parallel_optimizer``: train the adapters, freeze the base
  (frozen params get ``optax.set_to_zero`` and carry NO Adam state — the
  PEFT memory win is real, not cosmetic);
- :func:`lora_params` / :func:`strip_lora` — split a params tree into the
  adapter-only checkpoint and the base;
- :func:`merge_lora` — fold trained adapters into the base kernels
  (``kernel += (alpha/r) * A @ B``) producing a dense tree for serving with
  ``lora_rank=0`` modules.
"""

from __future__ import annotations

from typing import Any

import jax
import numpy as np


def lora_trainable(path: str) -> bool:
    """``trainable=`` predicate: only LoRA adapter params update."""
    return "lora_" in path


def _is_lora_leaf_path(path_keys) -> bool:
    """True when ANY path component names a LoRA factor — not just the
    leaf.  Adapter pytrees coming back from wrappers (optimizer state
    mirrors, orbax restore shims, per-device trees) can nest extra levels
    UNDER the ``lora_a``/``lora_b`` key (e.g. ``.../lora_a/value``); a
    last-key-only match silently dropped those leaves from
    :func:`lora_params`, truncating the adapter checkpoint."""
    return any("lora_" in str(getattr(k, "key", k)) for k in path_keys)


def lora_params(params: Any) -> Any:
    """The adapter-only subtree (for small LoRA checkpoints): non-adapter
    leaves are replaced with None (pruned on save by orbax/pytree users)."""
    flat, treedef = jax.tree_util.tree_flatten_with_path(params)
    return jax.tree_util.tree_unflatten(
        treedef, [leaf if _is_lora_leaf_path(p) else None for p, leaf in flat]
    )


def strip_lora(params: Any) -> Any:
    """Discard the adapters WITHOUT merging — the original base-model tree a
    ``lora_rank=0`` module expects (abandoning a fine-tune; after
    :func:`merge_lora` there is nothing left to strip — it already drops the
    adapter leaves)."""

    def strip(node):
        if isinstance(node, dict):
            return {k: strip(v) for k, v in node.items() if "lora_" not in k}
        return node

    return strip(params)


def merge_lora(params: Any, alpha: float) -> Any:
    """Fold adapters into their base kernels and drop them.

    Handles the two layouts the layers produce: plain linears
    (``lora_a``/``lora_b`` beside ``kernel``; fused kernels merge through a
    reshape) and the GQA QKV module (``lora_a_q``/``lora_b_q`` beside
    ``q_kernel`` etc.).  ``alpha`` is REQUIRED and must equal the modules'
    ``lora_alpha`` — a wrong value silently mis-scales every merged kernel.
    Returns a new tree; pass it to a ``lora_rank=0`` model."""

    def merge_pair(kernel, a, b):
        # a [..., in, r], b [..., r, *rest], kernel [..., in, *rest] — the
        # leading dims cover scan_layers/pipeline-stacked [L, ...] params
        a = np.asarray(jax.device_get(a))
        bm = np.asarray(jax.device_get(b))
        k = np.asarray(jax.device_get(kernel))
        r = a.shape[-1]
        lead = a.shape[:-2]
        delta = np.einsum(
            "...ir,...rk->...ik", a, bm.reshape(*lead, r, -1)
        ).reshape(k.shape)
        return (k + (alpha / r) * delta).astype(k.dtype)

    def walk(node):
        if not isinstance(node, dict):
            return node
        out = {}
        for key, val in node.items():
            if "lora_" in key:
                continue  # consumed below
            out[key] = walk(val)
        if "lora_a" in node and "lora_b" in node and "kernel" in node:
            out["kernel"] = merge_pair(node["kernel"], node["lora_a"], node["lora_b"])
        for t in ("q", "k", "v"):
            if f"lora_a_{t}" in node and f"{t}_kernel" in node:
                out[f"{t}_kernel"] = merge_pair(
                    node[f"{t}_kernel"], node[f"lora_a_{t}"], node[f"lora_b_{t}"]
                )
        return out

    return walk(params)
