"""Benchmark: Llama pretrain throughput on the available chip(s).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"} — always,
even when the TPU backend fails to initialize (round-1 failure mode: a
plugin hiccup raised out of ``jax.devices()`` and zeroed the whole round's
perf story).  Structure:

- the parent process never imports jax; it launches measurement attempts as
  subprocesses, so a cached backend-init error cannot poison a retry;
- a ladder of configs is tried in order (flash attention + big batch first,
  then dense, then smaller batches, then a CPU smoke run) and the first
  success wins;
- on total failure the parent emits a structured-error JSON line with
  ``value 0.0`` and the tail of the last stderr, rc=0;
- every successful measurement times TWO rungs over the same compiled
  program: prefetch OFF (host batch + per-step metric sync — the naive hot
  path) and prefetch ON (DevicePrefetcher staging + pipelined one-step-late
  fetch — the fit(prefetch=2, defer_metrics) production path).  The ON rung
  is the headline ``value``; ``host_blocked_frac`` / ``host_blocked_frac_sync``
  and ``tokens_per_sec_per_chip_sync`` make the overlap win visible in
  BENCH_*.json.

The reference publishes no absolute numbers (BASELINE.md), so ``vs_baseline``
is measured against the north-star target of 35% MFU (BASELINE.json): 1.0
means exactly 35% MFU on this chip; >1 beats the target.

Model: Llama-shaped decoder sized to fit a single v5e chip's 16 GB HBM for
full training (fp32 master params + fp32 Adam states + bf16 compute), seq
2048 — the single-chip slice of the Llama-2-7B TP=8 pretrain config
(reference tp_zero1_llama2_7b_hf_pretrain.sh:19-36).
"""

import argparse
import json
import math
import os
import subprocess
import sys
import time

# v5e (lite) peak bf16 FLOPs per chip
PEAK_FLOPS = {
    "tpu v5 lite": 197e12,
    "tpu v5e": 197e12,
    "tpu v5": 459e12,  # v5p
    "tpu v4": 275e12,
    "tpu v6 lite": 918e12,
    "cpu": 1e12,  # nominal, for smoke runs
}

# (platform, attention_impl, batch, remat, loss) tried in order; first
# success wins.  flash-without-remat leads: flash attention never
# materializes the [S,S] score matrix, so the 438M bench model's activations
# fit HBM un-remated and the recompute FLOPs remat would add (not counted by
# the MFU formula's 6*params accounting) are simply not spent.  A batch-16
# rung tops the ladder (selective remat to be HBM-safe): the measured
# 0.33-MFU b8 number left MXU headroom, and bigger batches amortize per-step
# overheads.  loss="chunked:N" computes the lm-head + CE per N-token chunk
# under remat — the [B,S,V] logits (the step's biggest activation, ~1 GB
# bf16 at b16/s2048/v32k, plus fp32 softmax residuals) never reach HBM,
# freeing the memory that gates the big-batch rungs (VERDICT r3 #1c).
LADDER = [
    ("tpu", "flash", 16, "none", "chunked:512"),
    ("tpu", "flash", 16, "selective", "chunked:512"),
    ("tpu", "flash", 16, "selective", "mean"),
    ("tpu", "flash", 8, "none", "chunked:512"),
    ("tpu", "flash", 8, "none", "mean"),
    ("tpu", "flash", 8, "selective", "mean"),
    ("tpu", "flash", 4, "selective", "mean"),
    ("tpu", "dense", 4, "selective", "mean"),
    ("tpu", "dense", 2, "selective", "mean"),
    ("cpu", "dense", 2, "none", "mean"),
]
# The 2026-07-31 healthy window measured >24-minute cold compiles on the big
# train-step programs (remote compile service, zero local CPU) — 900s killed
# rungs mid-compile.  With the persistent cache warm an attempt needs
# seconds, so the long budget only ever bites on the first cold program.
ATTEMPT_TIMEOUT_S = 2400
PROBE_TIMEOUT_S = 420
# After two full-budget timeouts (cold compiles eating the window), do NOT
# go straight to the CPU fallback: the watcher may have warmed OTHER rungs'
# cache entries in an earlier window — replay exactly these two at a warm-
# cache budget before giving up.  A warm rung completes in well under 600 s;
# a cold one fails fast enough not to sink the run.
RECOVERY_RUNGS = [
    ("tpu", "flash", 8, "selective", "mean"),   # round-3 proven program
    ("tpu", "dense", 2, "selective", "mean"),   # cheapest-compile canary
]
RECOVERY_TIMEOUT_S = 600


def peak_flops_for(device) -> float:
    kind = getattr(device, "device_kind", "cpu").lower()
    for k, v in PEAK_FLOPS.items():
        if kind.startswith(k):
            return v
    return 197e12


def run_measurement(platform: str, attn: str, batch: int, remat: str,
                    loss: str = "mean",
                    profile_out: "str | None" = None) -> dict:
    """Child-process body: build the model, time steps, return the result.

    Raises on any failure; the parent ladder decides what to try next."""
    import jax
    import jax.numpy as jnp

    import neuronx_distributed_tpu as nxd
    from neuronx_distributed_tpu.models.llama import (
        LlamaConfig,
        LlamaForCausalLM,
        causal_lm_loss,
    )
    from neuronx_distributed_tpu.trainer import (
        default_batch_spec,
        initialize_parallel_model,
        initialize_parallel_optimizer,
        make_train_step,
        transformer_flops_per_token,
        mfu,
    )

    devices = jax.devices()
    n = len(devices)
    on_tpu = devices[0].platform != "cpu"
    if platform == "tpu" and not on_tpu:
        # never report a silent-CPU-fallback number as a TPU measurement
        raise RuntimeError(f"requested tpu but jax.devices() -> {devices[0].platform}")

    if on_tpu:
        # ~400M-param Llama slice: 7B's hidden layout /4, seq 2048
        cfg = LlamaConfig(
            vocab_size=32000, hidden_size=1536, intermediate_size=4096,
            num_layers=12, num_heads=12, num_kv_heads=12, head_dim=128,
            max_seq_len=2048, sequence_parallel=n > 1, remat=remat,
            attention_impl=attn,
        )
        seq, steps, warmup = 2048, 10, 3
    else:  # CPU smoke mode
        cfg = LlamaConfig.tiny(sequence_parallel=False, remat="none")
        batch, seq, steps, warmup = 2, 64, 3, 1

    tp = n if n > 1 else 1
    nxd.initialize_model_parallel(tensor_parallel_size=tp, devices=devices)
    config = nxd.training_config(tensor_parallel_size=tp, learning_rate=1e-4)

    if loss.startswith("chunked"):
        from neuronx_distributed_tpu.models import make_causal_lm_loss_sum

        chunk = int(loss.split(":", 1)[1]) if ":" in loss else 512
        loss_fn = make_causal_lm_loss_sum(chunk_size=chunk)
    else:
        loss_fn = causal_lm_loss

    model = initialize_parallel_model(
        config, lambda: LlamaForCausalLM(cfg), (jnp.zeros((1, seq), jnp.int32),)
    )
    opt = initialize_parallel_optimizer(config, model)
    step = make_train_step(
        config, model, opt, loss_fn,
        batch_spec={"ids": default_batch_spec(), "labels": default_batch_spec()},
    )

    import numpy as np

    from neuronx_distributed_tpu.data.prefetch import DevicePrefetcher
    from neuronx_distributed_tpu.trainer.trainer import _batch_shardings

    np_ids = np.asarray(
        jax.random.randint(jax.random.PRNGKey(0), (batch, seq), 0,
                           cfg.vocab_size))
    # HOST batches for both passes: the host→device staging cost must be in
    # the measurement (it is exactly what the prefetch rung overlaps away)
    host_batch = {"ids": np_ids, "labels": np.roll(np_ids, -1, axis=1)}
    stage_shardings = _batch_shardings(
        model.mesh, {"ids": default_batch_spec(), "labels": default_batch_spec()})
    params, state = model.params, opt.state

    # Synchronization discipline (round-2 post-mortem): round 2 published a
    # 4,139%-MFU number — the ``block_until_ready(m["loss"])`` sync evidently
    # returned ~40x before execution finished on that run.  A round-3
    # side-by-side probe could NOT reproduce the early return (block waited
    # correctly), so the cause was a transient runtime/tunnel flake rather
    # than a systematic semantic — which is exactly why the sync here is
    # ``device_get`` of the final step's loss: the bytes cannot exist before
    # the step executed, and step i+1 consumes step i's params, so fetching
    # the LAST loss transitively proves every timed step ran.  Anything that
    # still slips through dies on the plausibility gate below.  The fetched
    # value is also checked finite: a step that executed but produced NaN is
    # a failed attempt, not a throughput number.
    # Compile accounting (obs.compile_ledger): jit compiles synchronously
    # before dispatch returns, so the FIRST warmup step's dispatch wall IS
    # the cold compile cost (with the persistent cache warm it measures the
    # cache replay — exactly what the next window will pay), and a later
    # dispatch of the same program is the warm cost.  These are first-class
    # BENCH fields (ROADMAP item 5), not ad-hoc timers: the ledger rows are
    # the record, the JSON fields read them back.
    from neuronx_distributed_tpu.obs.compile_ledger import CompileLedger

    ledger = CompileLedger()
    for i in range(warmup):
        t_disp = time.perf_counter()
        params, state, m = step(params, state, host_batch, jax.random.PRNGKey(i))
        ledger.record_compile(
            "train_step", "cold" if i == 0 else "warm",
            (time.perf_counter() - t_disp) * 1e3, kind="jit")
    if warmup < 2:
        # CPU smoke warms once; one extra dispatch gives the warm number
        t_disp = time.perf_counter()
        params, state, m = step(params, state, host_batch, jax.random.PRNGKey(0))
        ledger.record_compile("train_step", "warm",
                              (time.perf_counter() - t_disp) * 1e3, kind="jit")
    ledger.declare_warmup_done("bench")
    compile_walls = [r["wall_ms"] for r in ledger.rows
                     if r["event"] == "compile"]
    compile_cold_ms, compile_warm_ms = compile_walls[0], compile_walls[-1]
    float(jax.device_get(m["loss"]))

    # Prefetch-OFF rung: the naive hot path — a host batch handed to the
    # jitted step (implicit h2d) and a blocking per-step metric fetch.
    # host_blocked_frac_sync is the fraction of wall time the host spent
    # inside those fetches (≈ the device time the host serialized behind).
    t0 = time.perf_counter()
    blocked_s = 0.0
    for i in range(steps):
        params, state, m = step(params, state, host_batch, jax.random.PRNGKey(i))
        tb = time.perf_counter()
        loss_val = float(jax.device_get(m["loss"]))
        blocked_s += time.perf_counter() - tb
    dt_sync = time.perf_counter() - t0
    if not math.isfinite(loss_val):
        raise RuntimeError(f"non-finite loss after {warmup + steps} steps: {loss_val}")
    tokens_per_sec_sync = batch * seq * steps / dt_sync
    host_blocked_frac_sync = blocked_s / max(dt_sync, 1e-9)

    # Prefetch-ON rung (the async hot path, and the headline number):
    # batches staged onto the device ahead of the step by a background
    # thread, metric fetch pipelined one step behind the dispatch — the
    # same overlap fit(prefetch=N, defer_metrics=True) runs in production.
    # staged (sharding-committed) inputs are a DIFFERENT jit cache key than
    # the host batches above — one untimed warm step keeps the retrace out
    # of the timed window
    params, state, m = step(params, state,
                            jax.device_put(host_batch, stage_shardings),
                            jax.random.PRNGKey(0))
    float(jax.device_get(m["loss"]))
    prefetcher = DevicePrefetcher(lambda s: host_batch, depth=2,
                                  shardings=stage_shardings)
    # --profile-out: capture an XLA device profile of exactly the headline
    # (prefetch-ON) rung — the window whose number gets published
    from contextlib import nullcontext

    from neuronx_distributed_tpu.obs.tracing import device_trace

    prof = device_trace(profile_out) if profile_out else nullcontext()
    try:
        with prof:
            t0 = time.perf_counter()
            blocked_s = 0.0
            m_prev = None
            for i in range(steps):
                staged = prefetcher.get(i)
                params, state, m = step(params, state, staged,
                                        jax.random.PRNGKey(i))
                if m_prev is not None:  # pipelined: read i-1 behind i
                    tb = time.perf_counter()
                    float(jax.device_get(m_prev["loss"]))
                    blocked_s += time.perf_counter() - tb
                m_prev = m
            tb = time.perf_counter()
            loss_val = float(jax.device_get(m["loss"]))
            blocked_s += time.perf_counter() - tb
            dt = time.perf_counter() - t0
    finally:
        prefetcher.close()
    if not math.isfinite(loss_val):
        raise RuntimeError(
            f"non-finite loss after the prefetch pass: {loss_val}")
    host_blocked_frac = blocked_s / max(dt, 1e-9)

    tokens_per_sec = batch * seq * steps / dt
    tokens_per_sec_per_chip = tokens_per_sec / n
    fpt = transformer_flops_per_token(
        cfg.num_layers, cfg.hidden_size, cfg.intermediate_size, cfg.vocab_size,
        seq, cfg.num_heads, cfg.num_kv_heads, cfg.head_dim_,
    )
    peak = peak_flops_for(devices[0])
    achieved_mfu = mfu(tokens_per_sec_per_chip, fpt, peak)

    # Roofline attribution of the same rung through the shared perf layer
    # (obs.perf): per-chip model FLOPs joined with the measured wall —
    # mfu_model cross-checks achieved_mfu, pct_roofline is the
    # how-far-off-the-ceiling number BENCH_*.json trends across rounds.
    from neuronx_distributed_tpu.obs.perf import PerfAttribution, device_spec

    perf = PerfAttribution(spec=device_spec(devices[0]))
    perf.note_cost("train_step", fpt * batch * seq / n, 0.0)
    perf.note_phase("train_step", dt * 1e3, calls=float(steps))
    roll = perf.rollup()

    # Physical-plausibility gate: mfu() returns a FRACTION of chip peak; a
    # value >= 1 (tokens/s above peak_flops/flops_per_token) is impossible
    # and means the timing harness did not measure the device.  Hard-fail
    # the attempt so an unsynchronized runtime can never publish a number
    # (ADVICE r2: no super-peak measurement may be recorded as a success).
    ceiling = peak / fpt
    if not (0.0 < achieved_mfu < 1.0):
        raise RuntimeError(
            f"implausible measurement: {tokens_per_sec_per_chip:,.0f} tokens/s/chip "
            f"=> mfu={achieved_mfu:.3f} (ceiling {ceiling:,.0f} tokens/s/chip at "
            f"mfu=1.0); the timed loop did not synchronize with device execution"
        )

    return {
        "metric": "llama_pretrain_tokens_per_sec_per_chip",
        "value": round(tokens_per_sec_per_chip, 2),
        "unit": (
            f"tokens/s/chip (mfu={achieved_mfu:.3f}, attn={attn}, batch={batch},"
            f" remat={remat}, loss={loss}, prefetch=2,"
            f" model={model.num_parameters()/1e6:.0f}M, seq={seq},"
            f" device={devices[0].device_kind};"
            f" sync rung: {tokens_per_sec_sync / n:,.0f} tok/s/chip,"
            f" host_blocked {host_blocked_frac_sync:.3f})"
        ),
        "vs_baseline": round(achieved_mfu / 0.35, 3),
        # the overlap story: host-blocked wall-time fraction with the async
        # hot path on (prefetch + pipelined metric fetch) vs the naive
        # per-step-sync loop on the same program
        "host_blocked_frac": round(host_blocked_frac, 4),
        "host_blocked_frac_sync": round(host_blocked_frac_sync, 4),
        "tokens_per_sec_per_chip_sync": round(tokens_per_sec_sync / n, 2),
        # first-class compile metrics (ROADMAP item 5, via the compile
        # ledger): cold = first dispatch of the train-step program (trace +
        # XLA compile, or the persistent-cache replay when warm), warm = a
        # later dispatch of the same compiled program
        "compile_cold_ms": round(compile_cold_ms, 1),
        "compile_warm_ms": round(compile_warm_ms, 1),
        # roofline attribution (obs.perf) over the headline rung
        "mfu_model": round(roll["mfu"], 4),
        "pct_roofline": round(roll["pct_roofline"], 4),
    }


def _enable_compilation_cache():
    """Persistent XLA compilation cache (round-3 post-mortem): the tunnel's
    healthy windows are short; with the cache pre-warmed, a measurement
    needs seconds of chip time instead of minutes of compile.  The cache
    lives in-repo so it survives across bench runs and the end-of-round
    driver invocation replays warm."""
    import jax

    cache_dir = os.environ.get(
        "JAX_COMPILATION_CACHE_DIR",
        os.path.join(os.path.dirname(os.path.abspath(__file__)), ".jax_cache"),
    )
    try:
        os.makedirs(cache_dir, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
    except Exception as e:  # noqa: BLE001 — cache is an optimization, never fatal
        print(f"compilation cache unavailable: {e}", file=sys.stderr)


def child_main(args) -> int:
    if args.platform == "cpu":
        # the JAX_PLATFORMS env value may be latched by a sitecustomize that
        # imports jax first; the config update always wins
        import jax

        jax.config.update("jax_platforms", "cpu")
    _enable_compilation_cache()
    if args.probe:
        import jax

        devs = jax.devices()
        if args.platform == "tpu" and devs[0].platform == "cpu":
            print("probe failed: jax fell back to cpu", file=sys.stderr)
            return 1
        print(f"probe ok: {len(devs)}x {devs[0].device_kind}", file=sys.stderr)
        return 0
    try:
        result = run_measurement(args.platform, args.attn, args.batch, args.remat,
                                 args.loss, profile_out=args.profile_out)
    except Exception as e:  # noqa: BLE001 — report, parent decides
        print(f"bench attempt failed: {type(e).__name__}: {e}", file=sys.stderr)
        return 1
    print(json.dumps(result))
    return 0


def _run_child(extra_args, timeout_s, env=None):
    cmd = [sys.executable, os.path.abspath(__file__), "--run", *extra_args]
    try:
        return subprocess.run(
            cmd, env=env or dict(os.environ), capture_output=True, text=True,
            timeout=timeout_s,
        )
    except subprocess.TimeoutExpired:
        return None


def probe_tpu() -> "tuple[bool, str]":
    """ONE bounded TPU-backend probe; returns ``(ok, err)``.  The r05 tail
    showed the "tpu probe: timed out after 420s" line repeating — each
    repeat burned PROBE_TIMEOUT_S re-learning the same dead tunnel.  The
    ladder now probes exactly once per run and every consumer (rung gating,
    recovery) reads the cached ``tpu_ok``/``last_err`` result instead of
    re-probing."""
    proc = _run_child(["--probe", "--platform=tpu"], PROBE_TIMEOUT_S)
    ok = proc is not None and proc.returncode == 0
    err = "" if ok else (
        f"tpu probe: timed out after {PROBE_TIMEOUT_S}s" if proc is None
        else f"tpu probe rc={proc.returncode}: "
        + " | ".join((proc.stderr or "").strip().splitlines()[-3:])
    )
    if err:
        print(err, file=sys.stderr)
    return ok, err


def parent_main(profile_out: "str | None" = None) -> int:
    # Step 1: bounded TPU-backend probe — a hung or broken plugin must not
    # consume the whole time budget (round-1 failure: init raised; observed
    # alternative: init hangs indefinitely).  Exactly one probe subprocess
    # (and at most one failure line) per bench run.
    tpu_ok, last_err = probe_tpu()

    # Step 2: measurement ladder, first success wins.  Two timed-out TPU
    # attempts stop the full-budget rungs (a compile-bound window, not an
    # OOM) and fall through to the warm-cache recovery rungs below.
    tpu_timeouts = 0

    def attempt(platform, attn, batch, remat, loss, timeout_s):
        """Returns ``(parsed_json_or_None, completed)``; ``completed`` is
        False exactly when the child timed out (a completed child may still
        have failed with rc != 0)."""
        nonlocal last_err, tpu_timeouts
        env = dict(os.environ)
        if platform == "cpu":
            env["JAX_PLATFORMS"] = "cpu"
        child_args = [f"--platform={platform}", f"--attn={attn}",
                      f"--batch={batch}", f"--remat={remat}", f"--loss={loss}"]
        if profile_out:
            child_args.append(f"--profile-out={profile_out}")
        proc = _run_child(child_args, timeout_s, env)
        if proc is None:
            last_err = f"{platform}/{attn}/b{batch}: timed out after {timeout_s}s"
            print(last_err, file=sys.stderr)
            if platform == "tpu":
                tpu_timeouts += 1
            return None, False
        if proc.returncode == 0:
            for line in reversed(proc.stdout.strip().splitlines()):
                line = line.strip()
                if line.startswith("{"):
                    try:
                        return json.loads(line), True
                    except json.JSONDecodeError:
                        continue
        tail = (proc.stderr or "").strip().splitlines()[-12:]
        last_err = f"{platform}/{attn}/b{batch} rc={proc.returncode}: " + " | ".join(tail[-3:])
        print("\n".join(tail), file=sys.stderr)
        return None, True

    attempted = set()
    for platform, attn, batch, remat, loss in LADDER:
        if platform == "tpu" and (not tpu_ok or tpu_timeouts >= 2):
            continue
        if platform == "cpu" and tpu_ok and tpu_timeouts >= 2:
            continue  # warm-cache recovery rungs first; cpu smoke last
        rung = (platform, attn, batch, remat, loss)
        parsed, completed = attempt(*rung, ATTEMPT_TIMEOUT_S)
        if completed:
            # only COMPLETED rungs are banked: a rung that timed out stays
            # eligible for the warm-cache recovery replay below — its compile
            # is now cached, so the retry is exactly the cheap case the
            # recovery pass exists for (ADVICE r5: both full-budget timeouts
            # landing on recovery rungs used to skip the replay entirely)
            attempted.add(rung)
        if parsed is not None:
            print(json.dumps(parsed))
            return 0

    if tpu_ok and tpu_timeouts >= 2:
        for rung in RECOVERY_RUNGS:
            if rung in attempted:
                continue
            parsed, _ = attempt(*rung, RECOVERY_TIMEOUT_S)
            if parsed is not None:
                print(json.dumps(parsed))
                return 0
        # last resort: the CPU smoke line so the driver still gets a number
        parsed, _ = attempt("cpu", "dense", 2, "none", "mean", ATTEMPT_TIMEOUT_S)
        if parsed is not None:
            print(json.dumps(parsed))
            return 0
    # Total failure: still emit one well-formed JSON line, rc 0.
    print(json.dumps({
        "metric": "llama_pretrain_tokens_per_sec_per_chip",
        "value": 0.0,
        "unit": f"tokens/s/chip (error: {last_err[:400]})",
        "vs_baseline": 0.0,
    }))
    return 0


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--run", action="store_true", help="internal: run one measurement")
    p.add_argument("--probe", action="store_true", help="internal: just init the backend")
    p.add_argument("--platform", default="tpu")
    p.add_argument("--attn", default="dense")
    p.add_argument("--batch", type=int, default=2)
    p.add_argument("--remat", default="selective")
    p.add_argument("--loss", default="mean")
    p.add_argument("--profile-out", default=None,
                   help="directory for an XLA device profile of the "
                        "headline rung (jax.profiler trace)")
    args = p.parse_args()
    sys.exit(child_main(args) if args.run
             else parent_main(profile_out=args.profile_out))


if __name__ == "__main__":
    main()
