"""Benchmark: Llama pretrain throughput on the available chip(s).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

The reference publishes no absolute numbers (BASELINE.md), so ``vs_baseline``
is measured against the north-star target of 35% MFU (BASELINE.json): a value
of 1.0 means exactly 35% MFU on this chip; >1 beats the target.

Model: Llama-shaped decoder sized to fit a single v5e chip's 16 GB HBM for
full training (fp32 master params + fp32 Adam states + bf16 compute), seq
2048 — the single-chip slice of the Llama-2-7B TP=8 pretrain config
(tp_zero1_llama2_7b_hf_pretrain.sh:19-36 in the reference).
"""

import json
import time

import jax
import jax.numpy as jnp


# v5e (lite) peak bf16 FLOPs per chip
PEAK_FLOPS = {
    "tpu v5 lite": 197e12,
    "tpu v5e": 197e12,
    "tpu v5": 459e12,  # v5p
    "tpu v4": 275e12,
    "tpu v6 lite": 918e12,
    "cpu": 1e12,  # nominal, for smoke runs
}


def peak_flops_for(device) -> float:
    kind = getattr(device, "device_kind", "cpu").lower()
    for k, v in PEAK_FLOPS.items():
        if kind.startswith(k):
            return v
    return 197e12


def main():
    import neuronx_distributed_tpu as nxd
    from neuronx_distributed_tpu.models.llama import (
        LlamaConfig,
        LlamaForCausalLM,
        causal_lm_loss,
    )
    from neuronx_distributed_tpu.trainer import (
        default_batch_spec,
        initialize_parallel_model,
        initialize_parallel_optimizer,
        make_train_step,
        transformer_flops_per_token,
        mfu,
    )

    devices = jax.devices()
    n = len(devices)
    on_tpu = devices[0].platform != "cpu"

    if on_tpu:
        # ~400M-param Llama slice: 7B's hidden/4 layout, seq 2048
        cfg = LlamaConfig(
            vocab_size=32000, hidden_size=1536, intermediate_size=4096,
            num_layers=12, num_heads=12, num_kv_heads=12, head_dim=128,
            max_seq_len=2048, sequence_parallel=n > 1, remat="selective",
        )
        batch, seq, steps, warmup = 2, 2048, 10, 3
    else:  # CPU smoke mode
        cfg = LlamaConfig.tiny(sequence_parallel=False, remat="none")
        batch, seq, steps, warmup = 2, 64, 3, 1

    tp = n if n > 1 else 1
    nxd.initialize_model_parallel(tensor_parallel_size=tp, devices=devices)
    config = nxd.training_config(tensor_parallel_size=tp, learning_rate=1e-4)

    model = initialize_parallel_model(
        config, lambda: LlamaForCausalLM(cfg), (jnp.zeros((1, seq), jnp.int32),)
    )
    opt = initialize_parallel_optimizer(config, model)
    step = make_train_step(
        config, model, opt, causal_lm_loss,
        batch_spec={"ids": default_batch_spec(), "labels": default_batch_spec()},
    )

    ids = jax.random.randint(jax.random.PRNGKey(0), (batch, seq), 0, cfg.vocab_size)
    data = {"ids": ids, "labels": jnp.roll(ids, -1, axis=1)}
    params, state = model.params, opt.state

    for i in range(warmup):
        params, state, m = step(params, state, data, jax.random.PRNGKey(i))
    jax.block_until_ready(m["loss"])

    t0 = time.perf_counter()
    for i in range(steps):
        params, state, m = step(params, state, data, jax.random.PRNGKey(i))
    jax.block_until_ready(m["loss"])
    dt = time.perf_counter() - t0

    tokens_per_sec = batch * seq * steps / dt
    tokens_per_sec_per_chip = tokens_per_sec / n
    fpt = transformer_flops_per_token(
        cfg.num_layers, cfg.hidden_size, cfg.intermediate_size, cfg.vocab_size,
        seq, cfg.num_heads, cfg.num_kv_heads, cfg.head_dim_,
    )
    achieved_mfu = mfu(tokens_per_sec_per_chip, fpt, peak_flops_for(devices[0]))

    print(json.dumps({
        "metric": "llama_pretrain_tokens_per_sec_per_chip",
        "value": round(tokens_per_sec_per_chip, 2),
        "unit": f"tokens/s/chip (mfu={achieved_mfu:.3f}, model={model.num_parameters()/1e6:.0f}M, seq={seq})",
        "vs_baseline": round(achieved_mfu / 0.35, 3),
    }))


if __name__ == "__main__":
    main()
