"""HF ↔ framework checkpoint conversion CLI.

Script-level counterpart of the reference's
``examples/training/llama2/convert_checkpoints.py`` (HF↔NxD state-dict
conversion), built on :mod:`neuronx_distributed_tpu.convert`:

    # HF -> framework (orbax dir consumable by trainer.load_checkpoint)
    python examples/convert_checkpoints.py to-framework \
        --family llama --hf /path/to/hf_model_dir --out /tmp/fw_ckpt \
        --config llama2_7b

    # framework -> HF (safetensors)
    python examples/convert_checkpoints.py to-hf \
        --family llama --ckpt /tmp/fw_ckpt --out /tmp/hf_out --config llama2_7b

HF side accepts a directory containing ``*.safetensors`` (preferred) or
``pytorch_model*.bin`` shards.  The framework side is the same orbax layout
``trainer.checkpoint`` reads ("model" payload of a tag dir).
"""

import argparse
import glob
import json
import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


def _load_hf_state_dict(path):
    sd = {}
    st_files = sorted(glob.glob(os.path.join(path, "*.safetensors")))
    if st_files:
        from safetensors import safe_open

        for f in st_files:
            with safe_open(f, framework="np") as fh:
                for k in fh.keys():
                    sd[k] = fh.get_tensor(k)
        return sd
    bin_files = sorted(glob.glob(os.path.join(path, "pytorch_model*.bin"))) or sorted(
        glob.glob(os.path.join(path, "*.pt"))
    )
    if not bin_files:
        raise FileNotFoundError(f"no *.safetensors or pytorch_model*.bin under {path}")
    import torch

    for f in bin_files:
        blob = torch.load(f, map_location="cpu", weights_only=True)
        for k, v in blob.items():
            sd[k] = v.numpy() if hasattr(v, "numpy") else np.asarray(v)
    return sd


def _save_hf_state_dict(sd, path):
    os.makedirs(path, exist_ok=True)
    try:
        from safetensors.numpy import save_file

        save_file({k: np.ascontiguousarray(v) for k, v in sd.items()},
                  os.path.join(path, "model.safetensors"))
    except ImportError:  # pragma: no cover - safetensors ships with transformers
        import torch

        torch.save({k: torch.from_numpy(np.ascontiguousarray(v)) for k, v in sd.items()},
                   os.path.join(path, "pytorch_model.bin"))


def _family(args):
    # conversion is pure host-side layout algebra: never touch an accelerator
    # backend (the env may pin JAX_PLATFORMS to a hardware plugin; the config
    # update wins over the latched env value)
    import jax

    jax.config.update("jax_platforms", args.platform)
    from neuronx_distributed_tpu import convert as C

    def build_cfg(cls):
        if not args.config:
            return cls()
        if args.config.endswith(".json") or os.path.exists(args.config):
            with open(args.config) as f:
                return cls(**json.load(f))
        return getattr(cls, args.config)()

    if args.family == "llama":
        from neuronx_distributed_tpu.models.llama import LlamaConfig

        return build_cfg(LlamaConfig), C.llama_params_from_hf, C.llama_params_to_hf
    if args.family == "gpt_neox":
        from neuronx_distributed_tpu.models.gpt_neox import GPTNeoXConfig

        return build_cfg(GPTNeoXConfig), C.gpt_neox_params_from_hf, C.gpt_neox_params_to_hf
    if args.family == "bert":
        from neuronx_distributed_tpu.models.bert import BertConfig

        return build_cfg(BertConfig), C.bert_params_from_hf, C.bert_params_to_hf
    if args.family == "gemma":
        from neuronx_distributed_tpu.models.gemma import GemmaConfig

        return build_cfg(GemmaConfig), C.gemma_params_from_hf, C.gemma_params_to_hf
    if args.family == "gemma2":
        from neuronx_distributed_tpu.models.gemma import Gemma2Config

        return build_cfg(Gemma2Config), C.gemma2_params_from_hf, C.gemma2_params_to_hf
    raise ValueError(f"unknown family {args.family}")


def cmd_to_framework(args):
    import orbax.checkpoint as ocp

    cfg, from_hf, _ = _family(args)
    sd = _load_hf_state_dict(args.hf)
    params = from_hf(sd, cfg)
    ocp.Checkpointer(ocp.StandardCheckpointHandler()).save(
        os.path.join(os.path.abspath(args.out), "model"),
        args=ocp.args.StandardSave(params), force=True,
    )
    n = sum(int(np.asarray(x).size) for x in _leaves(params))
    with open(os.path.join(args.out, "meta.json"), "w") as f:
        json.dump({"tag": "hf_import", "family": args.family, "config": args.config}, f)
    print(json.dumps({"params": n, "out": args.out}))


def cmd_to_hf(args):
    import orbax.checkpoint as ocp

    cfg, _, to_hf = _family(args)
    params = ocp.Checkpointer(ocp.StandardCheckpointHandler()).restore(
        os.path.join(os.path.abspath(args.ckpt), "model")
    )
    if "layers" in params and "head" in params:
        # pipeline-engine checkpoint ({embed, layers: stacked, head}): flatten
        # through layer_rows (uneven cuts / padding) to the standard tree
        import neuronx_distributed_tpu.convert as C

        stack_rows = next(iter(_leaves(params["layers"]))).shape[0]
        if args.layer_rows is None:
            if stack_rows != cfg.num_layers:
                raise SystemExit(
                    f"pipelined stack has {stack_rows} rows but the config has "
                    f"{cfg.num_layers} layers (uneven pipeline_cuts / padding): "
                    "pass --layer-rows with the PipelinedModel.layer_rows "
                    "mapping — an identity default would export padding rows "
                    "as layers")
            rows = list(range(cfg.num_layers))
        else:
            rows = [int(r) for r in args.layer_rows.split(",")]
            if len(rows) != cfg.num_layers or (rows and max(rows) >= stack_rows):
                raise SystemExit(
                    f"--layer-rows must list {cfg.num_layers} rows < {stack_rows}")
        flat = {
            "llama": C.llama_params_from_pipelined,
            "gpt_neox": C.gpt_neox_params_from_pipelined,
        }.get(args.family)
        if flat is None:
            raise SystemExit(f"pipelined checkpoints unsupported for {args.family}")
        params = flat(params, rows)
    sd = to_hf(params, cfg)
    _save_hf_state_dict(sd, args.out)
    print(json.dumps({"tensors": len(sd), "out": args.out}))


def _leaves(tree):
    if isinstance(tree, dict):
        for v in tree.values():
            yield from _leaves(v)
    else:
        yield tree


def main():
    p = argparse.ArgumentParser(description=__doc__)
    sub = p.add_subparsers(dest="cmd", required=True)
    for name, fn in (("to-framework", cmd_to_framework), ("to-hf", cmd_to_hf)):
        sp = sub.add_parser(name)
        sp.add_argument("--family", required=True, choices=["llama", "gpt_neox", "bert", "gemma", "gemma2"])
        sp.add_argument("--config", default=None,
                        # a preset name (tiny, llama2_7b, ...) or a JSON file
                        # of config-field overrides
                        help="preset name on the family config (e.g. llama2_7b, tiny)")
        sp.add_argument("--platform", default="cpu",
                        help="jax platform for the conversion (default cpu)")
        sp.add_argument("--out", required=True)
        if name == "to-framework":
            sp.add_argument("--hf", required=True, help="HF model directory")
        else:
            sp.add_argument("--ckpt", required=True, help="framework checkpoint tag dir")
            sp.add_argument("--layer-rows", default=None,
                            help="comma-separated stack row of each real layer for "
                                 "pipeline-engine checkpoints with uneven cuts / "
                                 "padding (default: identity 0..num_layers-1)")
        sp.set_defaults(fn=fn)
    args = p.parse_args()
    sys.exit(args.fn(args))


if __name__ == "__main__":
    main()
