#!/usr/bin/env python
"""Inference runner CLI — trace / infer / benchmark / check-accuracy, the
framework-native analogue of the reference's
``examples/inference/runner.py:232-260`` command surface.

  # trace and save a compiled serving artifact
  python examples/inference/runner.py trace --preset tiny --tp 2 \
      --batch-size 2 --context-len 32 --max-total-len 64 \
      --out /tmp/traced --virtual-devices 8

  # generate from the saved artifact
  python examples/inference/runner.py infer --model /tmp/traced \
      --max-new-tokens 16

  # per-token latency stats
  python examples/inference/runner.py benchmark --model /tmp/traced \
      --max-new-tokens 64

  # cached decode vs teacher-forced full forward
  python examples/inference/runner.py check-accuracy --preset tiny --tp 2 \
      --batch-size 2 --context-len 32 --max-total-len 64 --virtual-devices 8

  # continuous-batching serving demo (Poisson arrivals, streamed tokens)
  python examples/inference/runner.py serve --preset tiny --batch-size 3 \
      --context-len 16 --max-total-len 32 --num-requests 6 --rate 50

  # batched speculative serving over paged KV (--draft equal to --preset
  # is the draft == target control: acceptance 1.0, tokens/step ~ k+1)
  python examples/inference/runner.py serve --preset tiny --batch-size 3 \
      --context-len 16 --max-total-len 64 --page-size 8 \
      --draft tiny --spec-k 4 --num-requests 6
"""

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))


def build_model(args, preset=None, seed=None):
    import jax
    import jax.numpy as jnp
    from flax import linen as nn
    from jax.sharding import NamedSharding, PartitionSpec as P

    import neuronx_distributed_tpu as nxd
    from neuronx_distributed_tpu.models import (
        Gemma2Config,
        Gemma2ForCausalLM,
        GemmaConfig,
        GemmaForCausalLM,
    )
    from neuronx_distributed_tpu.models.llama import LlamaConfig, LlamaForCausalLM
    from neuronx_distributed_tpu.parallel.mesh import (
        get_mesh, model_parallel_is_initialized,
    )
    from neuronx_distributed_tpu.trace import InferenceConfig, ParallelInferenceModel

    if not model_parallel_is_initialized():
        nxd.initialize_model_parallel(tensor_parallel_size=args.tp)
    else:
        from neuronx_distributed_tpu.parallel.mesh import get_tensor_parallel_size

        if get_tensor_parallel_size() != args.tp:
            raise SystemExit(
                f"model parallel already initialized with tp="
                f"{get_tensor_parallel_size()}, but --tp {args.tp} requested")
    on_tpu = jax.default_backend() == "tpu"
    cfg_cls, model_cls = {
        "llama": (LlamaConfig, LlamaForCausalLM),
        "gemma": (GemmaConfig, GemmaForCausalLM),
        "gemma2": (Gemma2Config, Gemma2ForCausalLM),
    }[getattr(args, "family", "llama")]
    cfg = getattr(cfg_cls, preset or args.preset)(
        max_seq_len=args.max_total_len,
        sequence_parallel=False,
        remat="none",
        dtype=jnp.bfloat16 if on_tpu else jnp.float32,
        param_dtype=jnp.float32,
    )
    module = model_cls(cfg)
    ids0 = jnp.zeros((args.batch_size, args.context_len), jnp.int32)
    params = module.init(jax.random.PRNGKey(args.seed if seed is None else seed), ids0)
    specs = nn.get_partition_spec(params)
    mesh = get_mesh()
    params = jax.tree.map(
        lambda x, s: jax.device_put(x, NamedSharding(mesh, s)),
        nn.unbox(params), specs,
        is_leaf=lambda x: isinstance(x, P) or not isinstance(x, dict))
    icfg = InferenceConfig(
        batch_size=args.batch_size, context_len=args.context_len,
        max_total_len=args.max_total_len,
        kv_cache_dtype=jnp.bfloat16 if on_tpu else jnp.float32,
        chunked_prefill=getattr(args, "chunked_prefill", False))
    return cfg, module, params, ParallelInferenceModel(module, params, icfg)


def cmd_trace(args):
    from neuronx_distributed_tpu.trace import parallel_model_save

    _, _, _, model = build_model(args)
    path = parallel_model_save(args.out, model)
    print(f"saved traced model to {path}")


def _prompt_ids(seed, batch_size, context_len, vocab):
    import jax

    return jax.random.randint(
        jax.random.PRNGKey(seed + 1), (batch_size, context_len), 0, vocab)


def cmd_infer(args):
    import jax

    from neuronx_distributed_tpu.trace import parallel_model_load

    model = parallel_model_load(args.model)
    cfg = model.config
    prompt = _prompt_ids(args.seed, cfg.batch_size, cfg.context_len, 256)
    lens = None
    if args.prompt_lens:
        lens = [int(x) for x in args.prompt_lens.split(",")]
        if len(lens) != cfg.batch_size:
            raise SystemExit(f"--prompt-lens needs {cfg.batch_size} comma-separated ints")
    out = model.generate(prompt, args.max_new_tokens,
                         temperature=args.temperature,
                         rng=jax.random.PRNGKey(args.seed) if args.temperature else None,
                         prompt_lens=lens)
    print(json.dumps({"generated": out[:, cfg.context_len:].tolist()}))


def cmd_spec_decode(args):
    import time

    from neuronx_distributed_tpu.trace import speculative_generate

    tcfg, _, _, target = build_model(args)
    _, _, _, draft = build_model(args, preset=args.draft_preset, seed=args.seed + 1)
    prompt = _prompt_ids(args.seed, args.batch_size, args.context_len, tcfg.vocab_size)

    # warm both paths, then time
    import jax

    jax.block_until_ready(target.generate(prompt, args.max_new_tokens))
    jax.block_until_ready(
        speculative_generate(target, draft, prompt, args.max_new_tokens, k=args.spec_k))
    t0 = time.perf_counter()
    want = target.generate(prompt, args.max_new_tokens)
    jax.block_until_ready(want)
    t_plain = time.perf_counter() - t0
    t0 = time.perf_counter()
    got, stats = speculative_generate(
        target, draft, prompt, args.max_new_tokens, k=args.spec_k, return_stats=True)
    jax.block_until_ready(got)
    t_spec = time.perf_counter() - t0
    import numpy as np

    identical = bool((np.asarray(got) == np.asarray(want)).all())
    print(json.dumps({
        "identical_to_target_greedy": identical,
        "plain_s": round(t_plain, 4), "spec_s": round(t_spec, 4),
        "speedup": round(t_plain / max(t_spec, 1e-9), 3), **stats,
    }))
    sys.exit(0 if identical else 1)


def cmd_serve(args):
    """Continuous-batching serving demo: drive ``ServingEngine`` (or, with
    ``--replicas N``, a ``FleetRouter`` over N in-process replicas) from a
    JSONL prompt file (``{"prompt_ids": [...], "max_new_tokens"?,
    "temperature"?}`` per line; random prompts when no file) with Poisson
    arrivals, streaming each token as a JSONL event and ending with one
    stats line."""
    import time

    import jax
    import numpy as np

    from neuronx_distributed_tpu.obs import MetricRegistry
    from neuronx_distributed_tpu.serving import (
        FleetRouter, Replica, Request, SamplingParams, ServingEngine,
        poisson_arrivals, replay, summarize_outputs)

    cfg, _, _, model = build_model(args)
    rs = np.random.RandomState(args.seed)
    specs = []
    if args.prompts:
        with open(args.prompts) as f:
            for line in f:
                line = line.strip()
                if line:
                    specs.append(json.loads(line))
        # the whole file unless --num-requests explicitly caps it
        if args.num_requests is not None:
            specs = specs[: args.num_requests]
    else:
        n = args.num_requests if args.num_requests is not None else 8
        specs = [
            {"prompt_ids": rs.randint(
                1, cfg.vocab_size,
                size=rs.randint(2, args.context_len + 1)).tolist()}
            for _ in range(n)
        ]
    if not specs:
        raise SystemExit("serve: no prompts (empty --prompts file or "
                         "--num-requests 0)")
    arrivals = poisson_arrivals(len(specs), args.rate, rs)

    def stream(req, tok):
        if not args.quiet:
            print(json.dumps({"event": "token", "request_id": req.request_id,
                              "token": int(tok)}), flush=True)

    paged_kw = {}
    if args.paged_kernel != "auto" and not args.page_size:
        raise SystemExit("--paged-kernel on|off needs --page-size: the "
                         "kernel walks block tables")
    if args.page_size:
        # paged KV: pool HBM is num_pages * page_bytes instead of B * T.
        # Default pool = the contiguous engine's footprint in pages PLUS the
        # reserved NULL page, so plain `--page-size N` is a true drop-in
        # (every workload the contiguous engine admits still fits) with
        # prefix reuse on top; shrink --num-pages to trade HBM for
        # admission backpressure.
        num_pages = args.num_pages or (
            args.batch_size * (args.max_total_len // args.page_size) + 1)
        paged_kw = dict(page_size=args.page_size, num_pages=num_pages,
                        paged_kernel={"auto": "auto", "on": True,
                                      "off": False}[args.paged_kernel])
    if args.kv_dtype == "int8":
        # int8 KV pages: same page count by default, half the HBM — or
        # shrink --num-pages less aggressively for ~2x the in-flight
        # requests at the fp pool's byte budget
        if not args.page_size:
            raise SystemExit("--kv-dtype int8 quantizes KV pages: pass "
                             "--page-size")
        paged_kw["kv_quant"] = "int8"
    n_adapters = args.adapters or 0
    if n_adapters:
        # multi-tenant demo: N random rank-4 LoRA adapters registered on
        # every engine, requests round-robined across them (JSONL prompt
        # specs may instead pin one explicitly via "adapter_id")
        if not args.page_size:
            raise SystemExit("--adapters needs --page-size: adapter paging "
                             "rides the paged engine")

        def make_store():
            import numpy as np

            from neuronx_distributed_tpu.tenancy import (
                AdapterLayout, AdapterStore)

            H, NQ, NKV, D = (cfg.hidden_size, cfg.num_heads,
                             cfg.num_kv_heads, cfg.head_dim_)
            rank = 4
            layout = AdapterLayout.for_model(model, rank, 2048)
            # every adapter resident at once, plus the NULL page
            store = AdapterStore(
                layout, n_adapters * layout.pages_per_adapter + 1)
            for aid in range(1, n_adapters + 1):
                r2 = np.random.RandomState(args.seed + aid)
                store.register(aid, [{
                    "a_q": (r2.randn(H, rank) * 0.05).astype(np.float32),
                    "b_q": (r2.randn(rank, NQ * D) * 0.05).astype(np.float32),
                    "a_v": (r2.randn(H, rank) * 0.05).astype(np.float32),
                    "b_v": (r2.randn(rank, NKV * D) * 0.05).astype(np.float32),
                } for _ in range(cfg.num_layers)], alpha=8.0)
            return store
    if args.draft:
        # speculative serving: a co-batched draft proposes --spec-k tokens
        # per slot per step, the target verifies them in one batched chunk.
        # The draft preset shares the target's seed, so `--draft` equal to
        # `--preset` is the draft == target control (acceptance 1.0).
        if not args.page_size:
            raise SystemExit("--draft needs --page-size: speculative "
                             "serving runs over the paged KV cache")
        _, _, _, draft = build_model(args, preset=args.draft)
        paged_kw.update(draft=draft, spec_k=args.spec_k)
    tracer = None
    if args.trace_out:
        from neuronx_distributed_tpu.obs import Tracer

        tracer = Tracer()
    fleet = args.replicas > 1
    health = None
    if args.alerts_out:
        # the control room: default rule pack over the live registries,
        # alert edges streamed to alerts.jsonl; a fleet gets per-replica
        # monitors + one fleet monitor through the router, a bare engine
        # one serving-scope monitor
        os.makedirs(args.alerts_out, exist_ok=True)
        alerts_path = os.path.join(args.alerts_out, "alerts.jsonl")
        if os.path.exists(alerts_path):
            os.remove(alerts_path)  # the sink appends: a rerun starts fresh
        if fleet:
            from neuronx_distributed_tpu.obs.aggregate import FleetHealth

            health = FleetHealth(path=alerts_path, tracer=tracer)
        else:
            from neuronx_distributed_tpu.obs.health import (
                HealthMonitor,
                default_rules,
            )

            health = HealthMonitor(default_rules("serving"),
                                   path=alerts_path, tracer=tracer,
                                   eval_every=4)
    if fleet:
        # in-process fleet: N engines share the one compiled model (one
        # set of device params) but each owns its KV state — and, with
        # --adapters, its own adapter store (every adapter registered on
        # every replica, so a requeued clone is admissible anywhere);
        # --stats-out becomes the router's router_stats.jsonl instead of a
        # single engine's serving_stats.jsonl
        def make_factory(rid):
            def factory():
                kw = dict(paged_kw)
                if n_adapters:
                    kw["adapter_store"] = make_store()
                if tracer is not None:
                    # one shared ring, per-replica span tags: a request's
                    # trace stitches across replicas by its global id
                    kw["tracer"] = tracer.scoped(rid)
                return ServingEngine(
                    model, rng=jax.random.PRNGKey(args.seed),
                    registry=MetricRegistry(), **kw)
            return factory

        target = FleetRouter(
            [Replica(i, make_factory(i)) for i in range(args.replicas)],
            policy=args.routing, seed=args.seed, stats_path=args.stats_out,
            tracer=tracer, health=health)
    else:
        if n_adapters:
            paged_kw["adapter_store"] = make_store()
        target = engine = ServingEngine(
            model, rng=jax.random.PRNGKey(args.seed),
            stats_path=args.stats_out, tracer=tracer, health=health,
            **paged_kw)
    requests = [
        Request(
            request_id=i,
            prompt_ids=s["prompt_ids"],
            max_new_tokens=int(s.get("max_new_tokens", args.max_new_tokens)),
            sampling=SamplingParams(
                temperature=float(s.get("temperature", args.temperature))),
            stream_cb=stream,
            adapter_id=int(s.get(
                "adapter_id", (i % n_adapters) + 1 if n_adapters else 0)),
        )
        for i, s in enumerate(specs)
    ]

    def done(out):
        ev = {"event": "done", "request_id": out.request_id,
              "state": out.state, "tokens": list(out.token_ids)}
        if fleet:  # the id the caller submitted, pre-re-keying
            ev["client_id"] = target.client_id(out.request_id)
        print(json.dumps(ev), flush=True)

    msrv = None
    if args.metrics_port is not None:
        # live scrape endpoint for the run's duration: /metrics serves the
        # front door's registry (router metrics for a fleet, engine
        # metrics solo); /healthz answers 503 once liveness is gone
        from neuronx_distributed_tpu.obs.metrics_server import MetricsServer

        if fleet:
            def liveness():
                alive = sum(1 for r in target.replicas.values() if r.alive)
                return {"ok": alive > 0, "replicas": args.replicas,
                        "alive_replicas": alive,
                        "inflight": target.inflight}
        else:
            def liveness():
                return {"ok": True, "steps": engine._steps,
                        "active": engine.scheduler.active_count,
                        "queued": engine.scheduler.queue_depth}

        scopes = None
        if fleet:
            from neuronx_distributed_tpu.obs.aggregate import (
                FleetAggregator,
            )

            scopes = {"fleet":
                      FleetAggregator.for_router(target).prometheus_text}
        msrv = MetricsServer(registry=target.registry, health_fn=liveness,
                             monitor=health, scopes=scopes,
                             port=args.metrics_port)
        endpoints = ["/metrics", "/healthz"]
        if scopes:
            endpoints.append("/metrics?scope=fleet")
        print(json.dumps({"event": "metrics_server", "port": msrv.port,
                          "endpoints": endpoints}),
              flush=True)

    t0 = time.monotonic()
    try:
        outputs = replay(target, arrivals, requests, on_output=done,
                         tracer=tracer)
    finally:
        if msrv is not None:
            msrv.close()
    wall = time.monotonic() - t0
    if tracer is not None:
        from neuronx_distributed_tpu.obs.schemas import validate_jsonl

        os.makedirs(args.trace_out, exist_ok=True)
        ev = os.path.join(args.trace_out, "trace_events.jsonl")
        ch = os.path.join(args.trace_out, "trace.json")
        tracer.export_jsonl(ev)
        tracer.export_chrome(ch)
        validate_jsonl("trace_event", ev)
        print(json.dumps({"event": "trace", "trace_events": ev,
                          "trace_perfetto": ch}), flush=True)
    if health is not None:
        from neuronx_distributed_tpu.obs.schemas import validate_jsonl

        health.close()
        ap = os.path.join(args.alerts_out, "alerts.jsonl")
        print(json.dumps({"event": "alerts", "alerts": ap,
                          "edges": validate_jsonl("alert", ap)}),
              flush=True)
    if fleet:
        snap = target.registry.snapshot()
        prefix = target.fleet_prefix_stats()
        target.close()
        hits = snap.get("router/affinity_hits_total", 0.0)
        misses = snap.get("router/affinity_misses_total", 0.0)
        summary = summarize_outputs(outputs, wall)
        summary.update({
            "replicas": args.replicas,
            "routing": target.policy.name,
            "dispatched": int(snap.get("router/dispatched_total", 0)),
            "requeued": int(snap.get("router/requeued_total", 0)),
            "failovers": int(snap.get("router/failovers_total", 0)),
            "affinity_hit_rate": (round(hits / (hits + misses), 4)
                                  if hits + misses else None),
        })
        if args.page_size:
            summary["fleet_prefix_hit_rate"] = prefix["prefix_hit_rate"]
            summary["prefills_skipped"] = prefix["prefills_skipped"]
        if n_adapters:
            summary["adapters"] = n_adapters
        print(json.dumps(summary))
        return
    engine.close()
    snap = engine.registry.snapshot()
    ttfts = [o.ttft_ms for o in outputs.values() if o.ttft_ms is not None]
    summary = {
        "requests": len(outputs),
        "finished": int(snap.get("serving/finished_total", 0)),
        "tokens": int(snap.get("serving/tokens_total", 0)),
        "ttft_p50_ms": float(np.percentile(ttfts, 50)) if ttfts else None,
        "wall_s": round(wall, 4),
        "tokens_per_s": (int(snap.get("serving/tokens_total", 0)) /
                         max(wall, 1e-9)),
    }
    if args.page_size:
        summary["kv_pages_in_use"] = int(snap.get("kvcache/pages_in_use", 0))
        summary["prefix_hits"] = int(snap.get("kvcache/prefix_hits_total", 0))
        summary["prefills_skipped"] = int(
            snap.get("kvcache/prefill_skipped_total", 0))
    if args.kv_dtype == "int8":
        summary["quant_page_writes"] = int(
            snap.get("kvcache/quant_pages_total", 0))
    if n_adapters:
        summary["adapters_resident"] = int(
            snap.get("tenancy/adapters_resident", 0))
        summary["adapter_loads"] = int(
            snap.get("tenancy/adapter_loads_total", 0))
        summary["adapter_hits"] = int(
            snap.get("tenancy/adapter_hits_total", 0))
    if args.draft:
        proposed = snap.get("serving/spec_proposed_total", 0.0)
        rounds = snap.get("serving/spec_rounds_total", 0.0)
        summary["tokens_per_step"] = (
            round(snap.get("serving/spec_committed_total", 0.0) / rounds, 4)
            if rounds else None)
        summary["acceptance_rate"] = (
            round(snap.get("serving/spec_accepted_total", 0.0) / proposed, 4)
            if proposed else None)
    print(json.dumps(summary))


def cmd_benchmark(args):
    from neuronx_distributed_tpu.trace import parallel_model_load

    model = parallel_model_load(args.model)
    stats = model.benchmark(max_new_tokens=args.max_new_tokens)
    print(json.dumps(stats, indent=2))


def cmd_check_accuracy(args):
    import jax
    import jax.numpy as jnp
    import numpy as np

    cfg, module, params, model = build_model(args)
    prompt = _prompt_ids(args.seed, args.batch_size, args.context_len, cfg.vocab_size)
    out = model.generate(prompt, args.max_new_tokens)
    full = jax.jit(module.apply)(params, out)
    ok = True
    for t in range(args.context_len, args.context_len + args.max_new_tokens):
        pred = np.asarray(jnp.argmax(full[:, t - 1, :], axis=-1))
        if not (pred == np.asarray(out[:, t])).all():
            ok = False
            print(f"mismatch at position {t}")
    print(json.dumps({"inference_success": int(ok)}))
    sys.exit(0 if ok else 1)


def main():
    p = argparse.ArgumentParser(description=__doc__)
    sub = p.add_subparsers(dest="cmd", required=True)

    def common(sp, traced=False):
        sp.add_argument("--virtual-devices", type=int, default=None)
        sp.add_argument("--seed", type=int, default=0)
        sp.add_argument("--max-new-tokens", type=int, default=16)
        if traced:
            sp.add_argument("--model", required=True, help="saved artifact dir")
        else:
            sp.add_argument("--preset", default="tiny",
                            help="config preset on the family's Config class")
            sp.add_argument("--family", default="llama",
                            choices=["llama", "gemma", "gemma2"])
            sp.add_argument("--tp", type=int, default=1)
            sp.add_argument("--batch-size", type=int, default=1)
            sp.add_argument("--context-len", type=int, default=128)
            sp.add_argument("--max-total-len", type=int, default=256)
            sp.add_argument("--chunked-prefill", action="store_true",
                            help="also compile a chunk-prefill executable so "
                                 "prompts of any multiple of --context-len serve "
                                 "without re-tracing")

    sp = sub.add_parser("trace", help="compile + save a serving artifact")
    common(sp)
    sp.add_argument("--out", required=True)
    sp.set_defaults(fn=cmd_trace)

    sp = sub.add_parser("infer", help="generate from a saved artifact")
    sp.add_argument("--prompt-lens", default=None,
                    help="comma-separated per-example prompt lengths "
                         "(ragged batch, left-padded)")
    common(sp, traced=True)
    sp.add_argument("--temperature", type=float, default=0.0)
    sp.set_defaults(fn=cmd_infer)

    sp = sub.add_parser("benchmark", help="p50/p99 per-token latency")
    common(sp, traced=True)
    sp.set_defaults(fn=cmd_benchmark)

    sp = sub.add_parser("serve", help="continuous-batching serving demo: "
                                      "JSONL prompts, Poisson arrivals, "
                                      "streamed tokens + stats line")
    common(sp)
    sp.add_argument("--prompts", default=None,
                    help="JSONL prompt file ({'prompt_ids': [...]} per line; "
                         "random prompts when omitted)")
    sp.add_argument("--num-requests", type=int, default=None,
                    help="request count (default: whole --prompts file, or "
                         "8 random prompts)")
    sp.add_argument("--rate", type=float, default=20.0,
                    help="Poisson arrival rate, requests/s")
    sp.add_argument("--temperature", type=float, default=0.0)
    sp.add_argument("--stats-out", default=None,
                    help="serving_stats.jsonl output path")
    sp.add_argument("--quiet", action="store_true",
                    help="suppress per-token stream events")
    sp.add_argument("--page-size", type=int, default=None,
                    help="enable the paged KV cache with this page size in "
                         "tokens (must divide --context-len and "
                         "--max-total-len); repeated prompts then share "
                         "prefix pages and skip prefill")
    sp.add_argument("--num-pages", type=int, default=None,
                    help="paged KV pool size in pages (default: the "
                         "contiguous engine's batch*total footprint + the "
                         "reserved NULL page; smaller pools trade HBM for "
                         "admission backpressure)")
    sp.add_argument("--adapters", type=int, default=0,
                    help="multi-tenant demo: register this many random "
                         "rank-4 LoRA adapters and round-robin requests "
                         "across them (JSONL specs may pin 'adapter_id'); "
                         "needs --page-size")
    sp.add_argument("--kv-dtype", default="fp", choices=["fp", "int8"],
                    help="KV page dtype: int8 stores pages quantized with "
                         "per-page scale/zero (~2x pages per HBM byte at a "
                         "bounded logit drift); needs --page-size")
    sp.add_argument("--paged-kernel", default="auto",
                    choices=["auto", "on", "off"],
                    help="block-table-native decode kernel "
                         "(ops.paged_attention): auto = kernel on TPU at "
                         "tp 1, gather path elsewhere; needs --page-size")
    sp.add_argument("--draft", default=None,
                    help="enable speculative serving with this draft-model "
                         "preset (same family/seed as the target, so a "
                         "preset equal to --preset is the draft == target "
                         "control); needs --page-size")
    sp.add_argument("--spec-k", type=int, default=4,
                    help="draft tokens proposed per slot per round "
                         "(speculative serving; requires --draft)")
    sp.add_argument("--replicas", type=int, default=1,
                    help="serve through a FleetRouter over this many "
                         "in-process engine replicas (1 = a bare engine); "
                         "--stats-out then writes router_stats.jsonl")
    sp.add_argument("--metrics-port", type=int, default=None,
                    help="expose /metrics (Prometheus text over the live "
                         "registry) and /healthz (engine/fleet liveness) "
                         "on this port for the duration of the serve run "
                         "(0 = ephemeral; the chosen port is printed as a "
                         "metrics_server event)")
    sp.add_argument("--trace-out", default=None,
                    help="directory to drop request-lifecycle trace "
                         "artifacts into after the run: trace_events.jsonl "
                         "(schema-checked spans, stitched across replicas) "
                         "+ trace.json (Perfetto)")
    sp.add_argument("--alerts-out", default=None,
                    help="run under the default health-monitor rule pack "
                         "(fleet: per-replica + fleet monitors) and stream "
                         "schema-checked alert edges to "
                         "DIR/alerts.jsonl; with --metrics-port, /healthz "
                         "readiness then reflects firing-alert state (503 "
                         "on page severity) and a fleet exposes "
                         "/metrics?scope=fleet (replica-labeled merge)")
    sp.add_argument("--routing", default="prefix_affinity",
                    choices=["round_robin", "random", "least_loaded",
                             "prefix_affinity"],
                    help="fleet dispatch policy (with --replicas > 1); "
                         "prefix_affinity needs --page-size to have "
                         "fingerprints to steer by, else it degrades to "
                         "least-loaded")
    sp.set_defaults(fn=cmd_serve)

    sp = sub.add_parser("spec-decode", help="speculative decoding: verify + time vs plain greedy")
    common(sp)
    sp.add_argument("--draft-preset", default="tiny",
                    help="draft model preset on the same family "
                         "(should be much smaller than the target)")
    sp.add_argument("--spec-k", type=int, default=4, help="draft tokens per round")
    sp.set_defaults(fn=cmd_spec_decode)

    sp = sub.add_parser("check-accuracy", help="cached decode vs teacher forcing")
    common(sp)
    sp.set_defaults(fn=cmd_check_accuracy)

    args = p.parse_args()
    if args.virtual_devices:
        from neuronx_distributed_tpu.utils.common import ensure_virtual_devices

        ensure_virtual_devices(args.virtual_devices)
    args.fn(args)


if __name__ == "__main__":
    main()
