#!/usr/bin/env python
"""Llama pretraining launcher — the framework-native analogue of the
reference's ``tp_zero1_llama2_7b_hf_pretrain.py`` / ``run_llama_nxd.py``
harnesses: TP x SP x DP (+ ZeRO-1) training with checkpoint/resume, the
native token data loader (or synthetic data), throughput/MFU metrics and an
optional host timeline.

Examples
--------
Synthetic smoke on the 8-device CPU mesh:

  XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
  python examples/training/llama_pretrain.py --preset tiny --tp 2 \
      --steps 20 --batch-size 8 --seq-len 128

Real corpus (NXDT token file, see neuronx_distributed_tpu.data):

  python examples/training/llama_pretrain.py --preset llama2_7b --tp 8 \
      --data /path/corpus.nxdt --batch-size 64 --seq-len 4096 \
      --ckpt-dir /path/ckpts --resume
"""

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import jax
import jax.numpy as jnp
import numpy as np


def parse_args():
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--preset", default="tiny",
                   choices=["tiny", "llama2_7b", "llama2_13b", "llama2_70b", "llama3_8b", "qwen2_7b", "mixtral_8x7b"])
    p.add_argument("--tp", type=int, default=1, help="tensor parallel degree")
    p.add_argument("--pp", type=int, default=1, help="pipeline parallel degree")
    p.add_argument("--microbatches", type=int, default=1,
                   help="pipeline microbatches (pp>1)")
    p.add_argument("--pp-schedule", default="1f1b", choices=["1f1b", "gpipe"])
    p.add_argument("--cp", type=int, default=1, help="context parallel degree (ring attention)")
    p.add_argument("--kv-multiplier", type=int, default=1,
                   help="KV replication when num_kv_heads < tp")
    p.add_argument("--no-sp", action="store_true", help="disable sequence parallelism")
    p.add_argument("--no-zero1", action="store_true", help="disable ZeRO-1 state sharding")
    p.add_argument("--attention", default="dense", choices=["dense", "flash"])
    p.add_argument("--remat", default="selective", choices=["none", "selective", "full"])
    p.add_argument("--scan-layers", action="store_true",
                   help="lax.scan over the layer stack (constant compile time in depth)")
    p.add_argument("--batch-size", type=int, default=8, help="global batch size")
    p.add_argument("--seq-len", type=int, default=2048)
    p.add_argument("--steps", type=int, default=100)
    p.add_argument("--lr", type=float, default=3e-4)
    p.add_argument("--warmup-steps", type=int, default=10)
    p.add_argument("--seed", type=int, default=42)
    p.add_argument("--data", default=None, help="NXDT token file (synthetic data if unset)")
    p.add_argument("--packed", action="store_true",
                   help="treat --data as an eos-joined document stream: split, "
                        "first-fit pack with segment masking and per-document "
                        "RoPE positions (data.packing) instead of flat chunking")
    p.add_argument("--packed-eos-id", type=int, default=None,
                   help="eos id separating documents in --data (required with --packed)")
    p.add_argument("--ckpt-dir", default=None)
    p.add_argument("--ckpt-every", type=int, default=50)
    p.add_argument("--keep-ckpts", type=int, default=3)
    p.add_argument("--resume", action="store_true")
    p.add_argument("--metrics-file", default=None, help="JSON results file")
    p.add_argument("--timeline", default=None, help="Chrome-trace output path")
    p.add_argument("--scalar-dir", default=None,
                   help="TensorBoard/JSONL scalar stream dir (designated-process only)")
    p.add_argument("--bf16", action="store_true", help="bf16 compute (default fp32 off-TPU)")
    p.add_argument("--virtual-devices", type=int, default=None,
                   help="force an N-device virtual CPU mesh (dev/test runs)")
    args = p.parse_args()
    if args.packed and not args.data:
        p.error("--packed requires --data (an eos-joined NXDT document stream)")
    if args.packed and args.packed_eos_id is None:
        p.error("--packed requires --packed-eos-id")
    return args


def main():
    args = parse_args()
    import neuronx_distributed_tpu as nxd
    from neuronx_distributed_tpu.models.llama import (
        LlamaConfig,
        LlamaForCausalLM,
        causal_lm_loss,
    )
    from neuronx_distributed_tpu.trainer import (
        Throughput,
        TrainingMetrics,
        default_batch_spec,
        initialize_parallel_model,
        initialize_parallel_optimizer,
        load_checkpoint,
        make_train_step,
        mfu,
        newest_tag,
        save_checkpoint,
        transformer_flops_per_token,
    )
    from neuronx_distributed_tpu.utils import Timeline, initialize_distributed
    from neuronx_distributed_tpu.utils.common import ensure_virtual_devices

    if args.virtual_devices:
        ensure_virtual_devices(args.virtual_devices)
    initialize_distributed()
    nxd.initialize_model_parallel(
        tensor_parallel_size=args.tp,
        pipeline_parallel_size=args.pp,
        context_parallel_size=args.cp,
        kv_size_multiplier=args.kv_multiplier,
    )

    on_tpu = jax.default_backend() == "tpu"
    # one TrainingConfig drives dtypes, mesh, pipeline and optimizer
    config = nxd.training_config(
        tensor_parallel_size=args.tp,
        pipeline_parallel_size=args.pp,
        context_parallel_size=args.cp,
        kv_size_multiplier=args.kv_multiplier,
        num_microbatches=args.microbatches,
        schedule=args.pp_schedule,
        packed_inputs=args.packed and args.pp > 1,
        learning_rate=args.lr,
        lr_schedule="cosine",
        warmup_steps=args.warmup_steps,
        total_steps=max(args.steps, args.warmup_steps + 1),
        zero_one_enabled=not args.no_zero1,
        compute_dtype="bfloat16" if (args.bf16 or on_tpu) else "float32",
        param_dtype="float32",
        seed=args.seed,
    )
    cfg = getattr(LlamaConfig, args.preset)(
        max_seq_len=args.seq_len,
        sequence_parallel=not args.no_sp,
        attention_impl=args.attention,
        remat=args.remat,
        scan_layers=args.scan_layers,
        dtype=config.jnp_compute_dtype,
        param_dtype=config.jnp_param_dtype,
    )

    model = initialize_parallel_model(
        config, lambda: LlamaForCausalLM(cfg), (jnp.zeros((1, args.seq_len), jnp.int32),),
        seed=args.seed,
    )
    # warmup-cosine comes from the config contract (OptimizerConfig.lr_schedule)
    opt = initialize_parallel_optimizer(config, model)
    bspec = {"ids": default_batch_spec(), "labels": default_batch_spec()}
    if args.packed:
        bspec.update({"positions": default_batch_spec(),
                      "segment_ids": default_batch_spec()})
    step_fn = make_train_step(config, model, opt, causal_lm_loss, batch_spec=bspec)
    params, opt_state = model.params, opt.state

    start_step = 0
    if args.resume and args.ckpt_dir and newest_tag(args.ckpt_dir):
        params, opt_state, _, user = load_checkpoint(
            args.ckpt_dir, model_template=params, optimizer_template=opt_state)
        start_step = (user or {}).get("step", 0)
        print(f"resumed from step {start_step}")

    # data: NXDT corpus through the native loader, or synthetic
    dp = nxd.get_data_parallel_size()
    if args.data and args.packed:
        import numpy as np

        from neuronx_distributed_tpu.data import TokenDataset
        from neuronx_distributed_tpu.data.loader import read_token_file
        from neuronx_distributed_tpu.data.packing import pack_documents, segment_positions

        TokenDataset(args.data).validate_vocab(cfg.vocab_size)
        toks = np.asarray(read_token_file(args.data))
        cuts = np.where(toks == args.packed_eos_id)[0]
        docs = [d[d != args.packed_eos_id] for d in np.split(toks, cuts + 1)]
        docs = [d for d in docs if d.size]
        ids_all, labels_all, segs_all = pack_documents(
            docs, seq_len=args.seq_len, eos_id=args.packed_eos_id)
        pos_all = segment_positions(segs_all)
        n_rows = ids_all.shape[0]
        if n_rows < args.batch_size:
            raise SystemExit(
                f"packing produced {n_rows} rows < batch size {args.batch_size}")
        print(f"packed {len(docs)} documents into {n_rows} rows of {args.seq_len}")

        perm_cache = {}

        def epoch_perm(e):
            if e not in perm_cache:
                perm_cache.clear() if len(perm_cache) > 2 else None
                perm_cache[e] = np.random.RandomState(args.seed + int(e)).permutation(n_rows)
            return perm_cache[e]

        def next_batch(step):
            # exact one-pass-per-epoch shuffle: element i of the batch is
            # global sample step*B+i, mapped through its OWN epoch's
            # permutation — no duplicated/skipped rows at epoch boundaries
            B = args.batch_size
            idxs = np.arange(step * B, (step + 1) * B)
            epochs = idxs // n_rows
            sel = np.empty(B, np.int64)
            for e in np.unique(epochs):
                m = epochs == e
                sel[m] = epoch_perm(e)[idxs[m] % n_rows]
            return {"ids": jnp.asarray(ids_all[sel]),
                    "labels": jnp.asarray(labels_all[sel]),
                    "positions": jnp.asarray(pos_all[sel]),
                    "segment_ids": jnp.asarray(segs_all[sel])}
    elif args.data:
        from neuronx_distributed_tpu.data import TokenDataLoader, TokenDataset

        ds = TokenDataset(args.data)
        ds.validate_vocab(cfg.vocab_size)
        loader = TokenDataLoader(
            ds, batch_size=args.batch_size, seq_len=args.seq_len,
            dp_rank=0, dp_size=1, seed=args.seed)  # single-controller: full batch
        # resume at the right epoch so the shuffle order matches an
        # uninterrupted run (epoch = step // batches-per-epoch)
        loader.set_epoch(
            start_step // max(len(loader), 1),
            skip_batches=start_step % max(len(loader), 1),
        )
        data_iter = iter(loader)

        def next_batch(step):
            nonlocal data_iter
            b = next(data_iter, None)
            if b is None:
                loader.set_epoch(step // max(len(loader), 1))
                data_iter = iter(loader)
                b = next(data_iter)
            return {"ids": jnp.asarray(b["ids"]), "labels": jnp.asarray(b["labels"])}
    else:
        def next_batch(step):
            k = jax.random.fold_in(jax.random.PRNGKey(args.seed), step)
            ids = jax.random.randint(k, (args.batch_size, args.seq_len), 0, cfg.vocab_size)
            return {"ids": ids, "labels": jnp.roll(ids, -1, axis=1)}

    flops_tok = transformer_flops_per_token(
        cfg.num_layers, cfg.hidden_size, cfg.intermediate_size, cfg.vocab_size,
        args.seq_len, cfg.num_heads, cfg.num_kv_heads, cfg.head_dim_)
    tl = Timeline(args.timeline)
    thr = Throughput(args.batch_size)
    metrics = TrainingMetrics(args.metrics_file) if args.metrics_file else None
    from neuronx_distributed_tpu.trainer.scalar_log import ScalarWriter

    scalars = ScalarWriter(args.scalar_dir) if args.scalar_dir else None

    for step in range(start_step, args.steps):
        with tl.event("train_step"):
            batch = next_batch(step)
            params, opt_state, m = step_fn(params, opt_state, batch,
                                           jax.random.fold_in(jax.random.PRNGKey(0), step))
            loss = float(m["loss"])
        seqs = thr.step()
        toks = seqs * args.seq_len
        if scalars:
            scalars.scalars(step, loss=loss, grad_norm=float(m["grad_norm"]),
                            seq_per_sec=seqs)
        if step % 10 == 0 or step == args.steps - 1:
            line = {
                "step": step, "loss": round(loss, 4),
                "seq_per_sec": round(seqs, 2),
                "tokens_per_sec": round(toks, 1),
                "grad_norm": round(float(m["grad_norm"]), 4),
            }
            print(json.dumps(line), flush=True)
        tl.mark_step_end(step)
        if args.ckpt_dir and (step + 1) % args.ckpt_every == 0:
            # async: the save overlaps the next training steps; the next
            # save (or the final wait) finalizes it
            save_checkpoint(args.ckpt_dir, f"step_{step + 1}", params, opt_state,
                            user_content={"step": step + 1},
                            num_kept_ckpts=args.keep_ckpts, async_save=True)

    if args.ckpt_dir:
        save_checkpoint(args.ckpt_dir, f"step_{args.steps}", params, opt_state,
                        user_content={"step": args.steps}, num_kept_ckpts=args.keep_ckpts)
        from neuronx_distributed_tpu.trainer.checkpoint import wait_for_checkpoint

        wait_for_checkpoint()
    if scalars:
        scalars.close()
    if metrics:
        peak = 197e12 if on_tpu else 1e12
        metrics.update(final_loss=loss, peak_seq_per_sec=thr.peak,
                       mfu=mfu(toks, flops_tok, peak), steps=args.steps,
                       completed_steps=args.steps, resumed_from_step=start_step)
        metrics.write()
    print(f"done: final loss {loss:.4f}")


if __name__ == "__main__":
    main()
