#!/usr/bin/env python
"""Llama pretraining launcher — the framework-native analogue of the
reference's ``tp_zero1_llama2_7b_hf_pretrain.py`` / ``run_llama_nxd.py``
harnesses: TP x SP x DP (+ ZeRO-1) training with checkpoint/resume, the
native token data loader (or synthetic data), throughput/MFU metrics and an
optional host timeline.

Examples
--------
Synthetic smoke on the 8-device CPU mesh:

  XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
  python examples/training/llama_pretrain.py --preset tiny --tp 2 \
      --steps 20 --batch-size 8 --seq-len 128

Real corpus (NXDT token file, see neuronx_distributed_tpu.data):

  python examples/training/llama_pretrain.py --preset llama2_7b --tp 8 \
      --data /path/corpus.nxdt --batch-size 64 --seq-len 4096 \
      --ckpt-dir /path/ckpts --resume
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import jax
import jax.numpy as jnp
import numpy as np


def parse_args():
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--preset", default="tiny",
                   choices=["tiny", "llama2_7b", "llama2_13b", "llama2_70b", "llama3_8b", "llama31_8b", "qwen2_7b", "mistral_7b", "mixtral_8x7b"])
    p.add_argument("--tp", type=int, default=1, help="tensor parallel degree")
    p.add_argument("--pp", type=int, default=1, help="pipeline parallel degree")
    p.add_argument("--microbatches", type=int, default=1,
                   help="pipeline microbatches (pp>1)")
    p.add_argument("--pp-schedule", default="1f1b",
                   choices=["1f1b", "gpipe", "interleaved"])
    p.add_argument("--virtual-stages", type=int, default=1,
                   help="interleaved virtual stages per pp rank (with "
                        "--pp-schedule interleaved); divides the bubble by ~V")
    p.add_argument("--loss-chunk", type=int, default=0,
                   help="chunked lm-head+CE: compute the loss per N-token "
                        "sequence chunk so [B,S,V] logits never hit HBM "
                        "(0 = off; 512 is a good TPU value)")
    p.add_argument("--cp", type=int, default=1, help="context parallel degree (ring attention)")
    p.add_argument("--kv-multiplier", type=int, default=1,
                   help="KV replication when num_kv_heads < tp")
    p.add_argument("--no-sp", action="store_true", help="disable sequence parallelism")
    p.add_argument("--no-zero1", action="store_true", help="disable ZeRO-1 state sharding")
    p.add_argument("--attention", default="dense", choices=["dense", "flash"])
    p.add_argument("--remat", default="selective", choices=["none", "selective", "full"])
    p.add_argument("--scan-layers", action="store_true",
                   help="lax.scan over the layer stack (constant compile time in depth)")
    p.add_argument("--batch-size", type=int, default=8, help="global batch size")
    p.add_argument("--seq-len", type=int, default=2048)
    p.add_argument("--steps", type=int, default=100)
    p.add_argument("--lr", type=float, default=3e-4)
    p.add_argument("--warmup-steps", type=int, default=10)
    p.add_argument("--seed", type=int, default=42)
    p.add_argument("--data", default=None, help="NXDT token file (synthetic data if unset)")
    p.add_argument("--packed", action="store_true",
                   help="treat --data as an eos-joined document stream: split, "
                        "first-fit pack with segment masking and per-document "
                        "RoPE positions (data.packing) instead of flat chunking")
    p.add_argument("--packed-eos-id", type=int, default=None,
                   help="eos id separating documents in --data (required with --packed)")
    p.add_argument("--ckpt-dir", default=None)
    p.add_argument("--ckpt-every", type=int, default=50)
    p.add_argument("--keep-ckpts", type=int, default=3)
    p.add_argument("--ckpt-bf16", action="store_true",
                   help="downcast the model payload to bfloat16 on save "
                   "(half-size checkpoints; optimizer masters stay fp32)")
    p.add_argument("--ckpt-on-signal", action="store_true",
                   help="on SIGTERM/SIGINT, finish the current step, write "
                   "the final checkpoint, and exit cleanly (preemption-safe "
                   "training; pair with --resume on restart)")
    p.add_argument("--resume", action="store_true")
    p.add_argument("--metrics-file", default=None, help="JSON results file")
    p.add_argument("--timeline", default=None, help="Chrome-trace output path")
    p.add_argument("--scalar-dir", default=None,
                   help="TensorBoard/JSONL scalar stream dir (designated-process only)")
    p.add_argument("--bf16", action="store_true", help="bf16 compute (default fp32 off-TPU)")
    p.add_argument("--virtual-devices", type=int, default=None,
                   help="force an N-device virtual CPU mesh (dev/test runs)")
    args = p.parse_args()
    if args.ckpt_on_signal and not args.ckpt_dir:
        p.error("--ckpt-on-signal requires --ckpt-dir")
    if args.loss_chunk and args.pp > 1:
        p.error("--loss-chunk has no effect with --pp > 1: the pipeline "
                "engine owns the head+loss (its last stage computes per-"
                "microbatch logits already bounded by the microbatch size)")
    if args.packed and not args.data:
        p.error("--packed requires --data (an eos-joined NXDT document stream)")
    if args.packed and args.packed_eos_id is None:
        p.error("--packed requires --packed-eos-id")
    return args


def main():
    args = parse_args()
    import neuronx_distributed_tpu as nxd
    from neuronx_distributed_tpu.models.llama import (
        LlamaConfig,
        LlamaForCausalLM,
        make_causal_lm_loss_sum,
    )
    from neuronx_distributed_tpu.trainer import (
        TrainingMetrics,
        default_batch_spec,
        fit,
        initialize_parallel_model,
        initialize_parallel_optimizer,
        transformer_flops_per_token,
    )
    from neuronx_distributed_tpu.utils import Timeline, initialize_distributed
    from neuronx_distributed_tpu.utils.common import ensure_virtual_devices

    if args.virtual_devices:
        ensure_virtual_devices(args.virtual_devices)
    initialize_distributed()
    nxd.initialize_model_parallel(
        tensor_parallel_size=args.tp,
        pipeline_parallel_size=args.pp,
        context_parallel_size=args.cp,
        kv_size_multiplier=args.kv_multiplier,
    )

    on_tpu = jax.default_backend() == "tpu"
    # one TrainingConfig drives dtypes, mesh, pipeline and optimizer
    config = nxd.training_config(
        tensor_parallel_size=args.tp,
        pipeline_parallel_size=args.pp,
        context_parallel_size=args.cp,
        kv_size_multiplier=args.kv_multiplier,
        num_microbatches=args.microbatches,
        schedule=args.pp_schedule,
        virtual_stages=args.virtual_stages,
        packed_inputs=args.packed and args.pp > 1,
        learning_rate=args.lr,
        lr_schedule="cosine",
        warmup_steps=args.warmup_steps,
        total_steps=max(args.steps, args.warmup_steps + 1),
        zero_one_enabled=not args.no_zero1,
        compute_dtype="bfloat16" if (args.bf16 or on_tpu) else "float32",
        param_dtype="float32",
        seed=args.seed,
    )
    cfg = getattr(LlamaConfig, args.preset)(
        max_seq_len=args.seq_len,
        sequence_parallel=not args.no_sp,
        attention_impl=args.attention,
        remat=args.remat,
        scan_layers=args.scan_layers,
        dtype=config.jnp_compute_dtype,
        param_dtype=config.jnp_param_dtype,
    )

    model = initialize_parallel_model(
        config, lambda: LlamaForCausalLM(cfg), (jnp.zeros((1, args.seq_len), jnp.int32),),
        seed=args.seed,
    )
    # warmup-cosine comes from the config contract (OptimizerConfig.lr_schedule)
    opt = initialize_parallel_optimizer(config, model)
    bspec = {"ids": default_batch_spec(), "labels": default_batch_spec()}
    if args.packed:
        bspec.update({"positions": default_batch_spec(),
                      "segment_ids": default_batch_spec()})
    # token-exact (loss_sum, tok) loss; --loss-chunk > 0 additionally chunks
    # the lm-head+CE so [B,S,V] logits never materialize (TPU HBM saver)
    loss_fn = make_causal_lm_loss_sum(chunk_size=args.loss_chunk)

    # data: NXDT corpus through the native loader, or synthetic
    dp = nxd.get_data_parallel_size()
    if args.data and args.packed:
        import numpy as np

        from neuronx_distributed_tpu.data import TokenDataset
        from neuronx_distributed_tpu.data.loader import read_token_file
        from neuronx_distributed_tpu.data.packing import pack_documents, segment_positions

        TokenDataset(args.data).validate_vocab(cfg.vocab_size)
        toks = np.asarray(read_token_file(args.data))
        cuts = np.where(toks == args.packed_eos_id)[0]
        docs = [d[d != args.packed_eos_id] for d in np.split(toks, cuts + 1)]
        docs = [d for d in docs if d.size]
        ids_all, labels_all, segs_all = pack_documents(
            docs, seq_len=args.seq_len, eos_id=args.packed_eos_id)
        pos_all = segment_positions(segs_all)
        n_rows = ids_all.shape[0]
        if n_rows < args.batch_size:
            raise SystemExit(
                f"packing produced {n_rows} rows < batch size {args.batch_size}")
        print(f"packed {len(docs)} documents into {n_rows} rows of {args.seq_len}")

        perm_cache = {}

        def epoch_perm(e):
            if e not in perm_cache:
                perm_cache.clear() if len(perm_cache) > 2 else None
                perm_cache[e] = np.random.RandomState(args.seed + int(e)).permutation(n_rows)
            return perm_cache[e]

        def next_batch(step):
            # exact one-pass-per-epoch shuffle: element i of the batch is
            # global sample step*B+i, mapped through its OWN epoch's
            # permutation — no duplicated/skipped rows at epoch boundaries
            B = args.batch_size
            idxs = np.arange(step * B, (step + 1) * B)
            epochs = idxs // n_rows
            sel = np.empty(B, np.int64)
            for e in np.unique(epochs):
                m = epochs == e
                sel[m] = epoch_perm(e)[idxs[m] % n_rows]
            return {"ids": jnp.asarray(ids_all[sel]),
                    "labels": jnp.asarray(labels_all[sel]),
                    "positions": jnp.asarray(pos_all[sel]),
                    "segment_ids": jnp.asarray(segs_all[sel])}
    elif args.data:
        from neuronx_distributed_tpu.data import TokenDataLoader, TokenDataset

        ds = TokenDataset(args.data)
        ds.validate_vocab(cfg.vocab_size)
        loader = TokenDataLoader(
            ds, batch_size=args.batch_size, seq_len=args.seq_len,
            dp_rank=0, dp_size=1, seed=args.seed)  # single-controller: full batch
        L = max(len(loader), 1)
        state = {"iter": None, "expected": None}

        def next_batch(step):
            # step-indexed facade over the epoch iterator: any jump (fit()'s
            # resume, an epoch boundary) re-seeks by epoch + skip so the
            # shuffle order matches an uninterrupted run
            if state["expected"] != step:
                loader.set_epoch(step // L, skip_batches=step % L)
                state["iter"] = iter(loader)
            b = next(state["iter"], None)
            if b is None:
                loader.set_epoch(step // L)
                state["iter"] = iter(loader)
                b = next(state["iter"])
            state["expected"] = step + 1
            return {"ids": jnp.asarray(b["ids"]), "labels": jnp.asarray(b["labels"])}
    else:
        def next_batch(step):
            k = jax.random.fold_in(jax.random.PRNGKey(args.seed), step)
            ids = jax.random.randint(k, (args.batch_size, args.seq_len), 0, cfg.vocab_size)
            return {"ids": ids, "labels": jnp.roll(ids, -1, axis=1)}

    flops_tok = transformer_flops_per_token(
        cfg.num_layers, cfg.hidden_size, cfg.intermediate_size, cfg.vocab_size,
        args.seq_len, cfg.num_heads, cfg.num_kv_heads, cfg.head_dim_)
    metrics = TrainingMetrics(args.metrics_file) if args.metrics_file else None

    # the whole loop — step/eval/checkpoint/resume/logging — is fit()'s job
    res = fit(
        config, model, opt, next_batch,
        steps=args.steps,
        loss_fn=loss_fn,
        batch_spec=bspec,
        ckpt_dir=args.ckpt_dir,
        ckpt_every=args.ckpt_every,
        keep_ckpts=args.keep_ckpts,
        ckpt_save_dtype=jnp.bfloat16 if args.ckpt_bf16 else None,
        checkpoint_on_signal=args.ckpt_on_signal,
        resume=args.resume,
        scalar_dir=args.scalar_dir,
        metrics=metrics,
        timeline=Timeline(args.timeline) if args.timeline else None,
        flops_per_token=flops_tok,
        peak_flops=197e12 if on_tpu else 1e12,
        log_every=10,
    )
    print(f"done: final loss {res.final_loss:.4f}")


if __name__ == "__main__":
    main()
