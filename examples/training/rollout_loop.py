#!/usr/bin/env python
"""Rollout → train → swap: co-located generation and training with live
in-memory weight swaps — the RLHF-shaped serving/training loop with NO
checkpoint round-trip and NO engine restart.

One process owns both sides:

- a ``ServingEngine`` (continuous batching over a compiled
  ``ParallelInferenceModel``) generates rollouts — greedy continuations of
  a fixed prompt set under the CURRENT weights;
- ``fit()`` trains on those rollouts (self-distillation: the model learns
  to sharpen its own top-1 continuations, so the loss falls);
- every ``--swap-every`` optimizer steps a :class:`Callback.on_params`
  hook hands the LIVE param pytree to ``WeightSwapper.swap(...,
  source="memory")`` — the engine's weights advance mid-flight, no phase
  program recompiles (the compile ledger pins zero post-warmup rows), and
  the next rollout round generates under the NEW version.

The swap copies (host round-trip): the jitted train step donates its
param buffers, so the engine must own its bytes — see
``weights/swapper.py``.

Smoke on the single-device CPU mesh (~30 s):

  JAX_PLATFORMS=cpu python examples/training/rollout_loop.py \
      --steps 24 --swap-every 8

Emits fit()'s per-step JSON lines, one ``{"event": "swap", ...}`` line
per live swap, and a final summary line with ``loss_fell``, ``swaps``,
``post_warmup_compiles`` (must be 0) and the per-round rollout weight
versions (proving outputs flip to the new version exactly at the swap
boundary).
"""

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import jax
import jax.numpy as jnp
import numpy as np


def parse_args():
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--steps", type=int, default=24, help="optimizer steps")
    p.add_argument("--swap-every", type=int, default=8,
                   help="live-swap (and re-rollout) cadence in steps")
    p.add_argument("--tp", type=int, default=1)
    p.add_argument("--prompt-len", type=int, default=8,
                   help="prompt tokens per rollout (== engine context len)")
    p.add_argument("--rollout-tokens", type=int, default=8,
                   help="greedy tokens generated per rollout")
    p.add_argument("--rollout-requests", type=int, default=12,
                   help="rollouts per round (served over --serve-slots)")
    p.add_argument("--serve-slots", type=int, default=4,
                   help="engine batch size (continuous-batching slots)")
    p.add_argument("--batch-size", type=int, default=8,
                   help="training batch size (rows sampled per step)")
    p.add_argument("--lr", type=float, default=3e-3)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--swaps-out", default=None,
                   help="weight_swaps.jsonl audit-trail path")
    p.add_argument("--metrics-file", default=None, help="JSON results file")
    p.add_argument("--virtual-devices", type=int, default=None)
    return p.parse_args()


def main():
    args = parse_args()
    import neuronx_distributed_tpu as nxd
    from neuronx_distributed_tpu.models.llama import (
        LlamaConfig,
        LlamaForCausalLM,
        make_causal_lm_loss_sum,
    )
    from neuronx_distributed_tpu.obs import MetricRegistry
    from neuronx_distributed_tpu.obs.compile_ledger import CompileLedger
    from neuronx_distributed_tpu.serving import Request, ServingEngine
    from neuronx_distributed_tpu.trace import (
        InferenceConfig,
        ParallelInferenceModel,
    )
    from neuronx_distributed_tpu.trainer import (
        Callback,
        default_batch_spec,
        fit,
        initialize_parallel_model,
        initialize_parallel_optimizer,
    )
    from neuronx_distributed_tpu.utils import initialize_distributed
    from neuronx_distributed_tpu.utils.common import ensure_virtual_devices
    from neuronx_distributed_tpu.weights import WeightSwapper

    if args.virtual_devices:
        ensure_virtual_devices(args.virtual_devices)
    initialize_distributed()
    nxd.initialize_model_parallel(tensor_parallel_size=args.tp)

    P, M = args.prompt_len, args.rollout_tokens
    S = P + M  # training rows are exactly one prompt + its rollout
    config = nxd.training_config(
        tensor_parallel_size=args.tp,
        learning_rate=args.lr,
        lr_schedule="cosine",
        warmup_steps=2,
        total_steps=max(args.steps, 3),
        compute_dtype="float32",
        param_dtype="float32",
        seed=args.seed,
    )
    cfg = LlamaConfig.tiny(
        max_seq_len=S, sequence_parallel=False, remat="none",
        dtype=config.jnp_compute_dtype, param_dtype=config.jnp_param_dtype)
    model = initialize_parallel_model(
        config, lambda: LlamaForCausalLM(cfg),
        (jnp.zeros((1, S), jnp.int32),), seed=args.seed)
    opt = initialize_parallel_optimizer(config, model)
    loss_fn = make_causal_lm_loss_sum()

    # the serving side: its OWN module instance (inference-tuned apply:
    # no remat, no SP) over an independent COPY of the initial params —
    # fit()'s first donated step would otherwise invalidate the engine's
    # version-0 buffers
    icfg_model = LlamaConfig.tiny(
        max_seq_len=S, sequence_parallel=False, remat="none",
        dtype=jnp.float32, param_dtype=jnp.float32)
    infer_params = jax.tree.map(
        lambda x: jax.device_put(np.asarray(x)), model.params)
    infer = ParallelInferenceModel(
        LlamaForCausalLM(icfg_model), infer_params,
        InferenceConfig(batch_size=args.serve_slots, context_len=P,
                        max_total_len=S, kv_cache_dtype=jnp.float32))
    ledger = CompileLedger()
    engine = ServingEngine(infer, registry=MetricRegistry(),
                           compile_ledger=ledger)
    swapper = WeightSwapper(engine, path=args.swaps_out)

    rs = np.random.RandomState(args.seed)
    prompts = [rs.randint(1, cfg.vocab_size, size=P).tolist()
               for _ in range(args.rollout_requests)]
    rid_counter = [0]
    round_versions = []  # [(round, min_version, max_version)] per rollout

    def rollout_round():
        """Generate one greedy continuation per prompt under the engine's
        CURRENT weights; returns [N, S] rows of prompt + rollout."""
        for p in prompts:
            rid_counter[0] += 1
            engine.submit(Request(request_id=rid_counter[0], prompt_ids=p,
                                  max_new_tokens=M))
        outs = engine.run_until_complete(max_steps=1000)
        rows, versions = [], []
        by_id = {o.request_id: o for o in outs}
        base = rid_counter[0] - len(prompts)
        for i, p in enumerate(prompts):
            o = by_id[base + 1 + i]
            rows.append(p + list(o.token_ids))
            versions.append(o.weights_version)
        round_versions.append(
            (len(round_versions), min(versions), max(versions)))
        return np.asarray(rows, np.int32)

    buffer = {"rows": rollout_round()}  # round 0: version-0 weights
    # every phase program this loop ever needs (prefill, decode, slot
    # reuse) just compiled: one post-warmup ledger row from here on is a
    # regression, and a swap must add none
    engine.declare_warmup_done()

    # loss only over the GENERATED tokens (labels at P-1 .. S-2): the
    # rollout is the model's own top-1 stream — sharpening it is the
    # learnable part; the random prompt tokens are irreducible noise
    row_mask = np.zeros((args.batch_size, S), np.float32)
    row_mask[:, P - 1:S - 1] = 1.0
    row_mask = jnp.asarray(row_mask)

    def next_batch(step):
        rows = buffer["rows"]
        sel = np.random.RandomState(args.seed * 1000 + step).randint(
            0, rows.shape[0], size=args.batch_size)
        ids = jnp.asarray(rows[sel])
        return {"ids": ids, "labels": jnp.roll(ids, -1, axis=1),
                "mask": row_mask}

    class SwapCallback(Callback):
        """Every --swap-every steps: live-swap the trainer's params into
        the engine (in-memory, copied), then refresh the rollout buffer
        under the new version."""

        def __init__(self):
            self.swaps = []
            self.losses = []

        def on_step(self, step, metrics):
            self.losses.append(float(metrics["loss"]))

        def on_params(self, step, params, opt_state):
            if (step + 1) % args.swap_every or step + 1 >= args.steps:
                return
            mark = ledger.mark()
            version = swapper.swap(params, source="memory")
            compiles = ledger.compiles_since(mark)
            buffer["rows"] = rollout_round()
            self.swaps.append({"step": step + 1, "version": version,
                               "swap_compiles": compiles})
            print(json.dumps({"event": "swap", "step": step + 1,
                              "version": version,
                              "swap_compiles": compiles}), flush=True)

    cb = SwapCallback()
    bspec = {"ids": default_batch_spec(), "labels": default_batch_spec(),
             "mask": default_batch_spec()}
    res = fit(config, model, opt, next_batch, steps=args.steps,
              loss_fn=loss_fn, batch_spec=bspec, callbacks=[cb],
              log_every=max(args.swap_every // 2, 1))

    engine.close()
    swapper.close()
    head = float(np.mean(cb.losses[:3])) if cb.losses else float("nan")
    summary = {
        "event": "summary",
        "steps": res.steps_run,
        "first_loss": round(head, 4),
        "final_loss": round(res.final_loss, 4),
        "loss_fell": bool(res.final_loss < head),
        "swaps": len(cb.swaps),
        "versions": [s["version"] for s in cb.swaps],
        "post_warmup_compiles": ledger.compile_count(after_warmup_only=True),
        "rollout_rounds": len(round_versions),
        # (round, min, max): min == max per round — every rollout in a
        # round decoded under exactly one weights_version, and the version
        # steps up by one per swap
        "rollout_versions": round_versions,
    }
    print(json.dumps(summary), flush=True)
    if args.metrics_file:
        with open(args.metrics_file, "w") as f:
            json.dump(summary, f)
    ok = (summary["loss_fell"] and summary["swaps"] >= 2
          and summary["post_warmup_compiles"] == 0
          and all(lo == hi for _, lo, hi in round_versions))
    sys.exit(0 if ok else 1)


if __name__ == "__main__":
    main()
