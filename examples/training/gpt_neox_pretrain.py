#!/usr/bin/env python
"""GPT-NeoX pretraining launcher (reference:
``examples/training/tp_dp_gpt_neox_hf_pretrain/`` 6.9B/20B harnesses).

  python examples/training/gpt_neox_pretrain.py --preset tiny --tp 2 \
      --steps 20 --batch-size 8 --seq-len 128 --virtual-devices 8
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))


def main():
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--preset", default="tiny", choices=["tiny", "neox_6_9b", "neox_20b"])
    p.add_argument("--tp", type=int, default=1)
    p.add_argument("--pp", type=int, default=1, help="pipeline parallel degree")
    p.add_argument("--microbatches", type=int, default=1,
                   help="pipeline microbatches (pp>1)")
    p.add_argument("--no-sp", action="store_true")
    p.add_argument("--no-zero1", action="store_true")
    p.add_argument("--batch-size", type=int, default=8)
    p.add_argument("--seq-len", type=int, default=2048)
    p.add_argument("--steps", type=int, default=100)
    p.add_argument("--lr", type=float, default=1e-4)
    p.add_argument("--seed", type=int, default=42)
    p.add_argument("--data", default=None, help="NXDT token file (synthetic if unset)")
    p.add_argument("--virtual-devices", type=int, default=None)
    p.add_argument("--metrics-file", default=None, help="JSON results file")
    args = p.parse_args()

    from neuronx_distributed_tpu.utils.common import ensure_virtual_devices

    if args.virtual_devices:
        ensure_virtual_devices(args.virtual_devices)

    import jax
    import jax.numpy as jnp

    import neuronx_distributed_tpu as nxd
    from neuronx_distributed_tpu.models.gpt_neox import (
        GPTNeoXConfig,
        GPTNeoXForCausalLM,
        causal_lm_loss,
    )
    from neuronx_distributed_tpu.trainer import (
        TrainingMetrics,
        default_batch_spec,
        fit,
        initialize_parallel_model,
        initialize_parallel_optimizer,
    )
    from neuronx_distributed_tpu.utils import initialize_distributed

    initialize_distributed()
    nxd.initialize_model_parallel(tensor_parallel_size=args.tp,
                                  pipeline_parallel_size=args.pp)
    on_tpu = jax.default_backend() == "tpu"
    cfg = getattr(GPTNeoXConfig, args.preset)(
        max_seq_len=args.seq_len,
        sequence_parallel=not args.no_sp,
        dtype=jnp.bfloat16 if on_tpu else jnp.float32,
        param_dtype=jnp.float32,
    )
    config = nxd.training_config(
        tensor_parallel_size=args.tp, learning_rate=args.lr,
        pipeline_parallel_size=args.pp, num_microbatches=args.microbatches,
        zero_one_enabled=not args.no_zero1,
        compute_dtype="bfloat16" if on_tpu else "float32")
    model = initialize_parallel_model(
        config, lambda: GPTNeoXForCausalLM(cfg),
        (jnp.zeros((1, args.seq_len), jnp.int32),), seed=args.seed)
    opt = initialize_parallel_optimizer(config, model)

    if args.data:
        from neuronx_distributed_tpu.data import TokenDataLoader, TokenDataset

        ds = TokenDataset(args.data)
        ds.validate_vocab(cfg.vocab_size)
        loader = TokenDataLoader(ds, args.batch_size,
                                 args.seq_len, seed=args.seed)
        loader.set_epoch(0)
        it = iter(loader)

        def next_batch(step):
            # wrap into the next epoch on exhaustion (mirrors llama_pretrain)
            nonlocal it
            b = next(it, None)
            if b is None:
                loader.set_epoch(step // max(len(loader), 1))
                it = iter(loader)
                b = next(it)
            return {"ids": jnp.asarray(b["ids"]), "labels": jnp.asarray(b["labels"])}
    else:
        def next_batch(step):
            k = jax.random.fold_in(jax.random.PRNGKey(args.seed), step)
            ids = jax.random.randint(k, (args.batch_size, args.seq_len), 0, cfg.vocab_size)
            return {"ids": ids, "labels": jnp.roll(ids, -1, axis=1)}

    res = fit(
        config, model, opt, next_batch, steps=args.steps,
        loss_fn=causal_lm_loss,
        batch_spec={"ids": default_batch_spec(), "labels": default_batch_spec()},
        metrics=TrainingMetrics(args.metrics_file) if args.metrics_file else None,
        log_every=10,
    )
    print(f"done: final loss {res.final_loss:.4f}")


if __name__ == "__main__":
    main()
