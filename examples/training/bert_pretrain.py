#!/usr/bin/env python
"""BERT-large MLM+NSP pretraining launcher (reference:
``examples/training/tp_dp_bert_hf_pretrain/tp_dp_bert_large_hf_pretrain_hdf5.py``).

  python examples/training/bert_pretrain.py --preset tiny --tp 2 \
      --steps 20 --batch-size 8 --seq-len 128 --virtual-devices 8
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))


def main():
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--preset", default="tiny", choices=["tiny", "bert_large"])
    p.add_argument("--tp", type=int, default=1)
    p.add_argument("--batch-size", type=int, default=8)
    p.add_argument("--seq-len", type=int, default=128)
    p.add_argument("--steps", type=int, default=100)
    p.add_argument("--lr", type=float, default=1e-4)
    p.add_argument("--mask-prob", type=float, default=0.15)
    p.add_argument("--seed", type=int, default=42)
    p.add_argument("--virtual-devices", type=int, default=None)
    p.add_argument("--metrics-file", default=None, help="JSON results file")
    args = p.parse_args()

    from neuronx_distributed_tpu.utils.common import ensure_virtual_devices

    if args.virtual_devices:
        ensure_virtual_devices(args.virtual_devices)

    import jax
    import jax.numpy as jnp

    import neuronx_distributed_tpu as nxd
    from neuronx_distributed_tpu.models.bert import (
        BertConfig,
        BertForPreTraining,
        pretraining_loss,
    )
    from neuronx_distributed_tpu.trainer import (
        TrainingMetrics,
        default_batch_spec,
        fit,
        initialize_parallel_model,
        initialize_parallel_optimizer,
    )
    from neuronx_distributed_tpu.utils import initialize_distributed

    initialize_distributed()
    nxd.initialize_model_parallel(tensor_parallel_size=args.tp)
    on_tpu = jax.default_backend() == "tpu"
    cfg = getattr(BertConfig, args.preset)(
        dtype=jnp.bfloat16 if on_tpu else jnp.float32, param_dtype=jnp.float32)
    config = nxd.training_config(tensor_parallel_size=args.tp, learning_rate=args.lr,
                                 compute_dtype="bfloat16" if on_tpu else "float32")
    model = initialize_parallel_model(
        config, lambda: BertForPreTraining(cfg),
        (jnp.zeros((1, args.seq_len), jnp.int32),), seed=args.seed)
    opt = initialize_parallel_optimizer(config, model)
    spec = default_batch_spec()

    MASK = 103  # [MASK] in the BERT vocab
    # skip the special-token id range on the real vocab; tiny vocabs have no
    # such range to skip
    lo = 999 if cfg.vocab_size > 1000 else MASK + 1

    def next_batch(step):
        k = jax.random.fold_in(jax.random.PRNGKey(args.seed), step)
        k1, k2, k3 = jax.random.split(k, 3)
        ids = jax.random.randint(k1, (args.batch_size, args.seq_len), lo, cfg.vocab_size)
        mask = jax.random.bernoulli(k2, args.mask_prob, ids.shape)
        labels = jnp.where(mask, ids, -100)
        return {
            "ids": jnp.where(mask, MASK, ids),
            "mlm_labels": labels,
            "nsp_labels": jax.random.randint(k3, (args.batch_size,), 0, 2),
        }

    res = fit(
        config, model, opt, next_batch, steps=args.steps,
        loss_fn=pretraining_loss,
        batch_spec={"ids": spec, "mlm_labels": spec, "nsp_labels": spec},
        metrics=TrainingMetrics(args.metrics_file) if args.metrics_file else None,
        step_rng=True,  # BERT trains with dropout
        log_every=10,
    )
    print(f"done: final loss {res.final_loss:.4f}")


if __name__ == "__main__":
    main()
