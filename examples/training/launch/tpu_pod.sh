#!/usr/bin/env bash
# Multi-host launch on a TPU pod slice — the framework-native analogue of
# the reference's torchrun/SLURM launch scripts
# (reference examples/training/llama2/tp_zero1_llama2_7b_hf_pretrain/
#  tp_zero1_llama2_7b_hf_pretrain.sh:44-56).
#
# On Cloud TPU VMs, run the SAME command on every host of the slice (e.g.
# via `gcloud compute tpus tpu-vm ssh $NAME --worker=all --command=...`).
# jax.distributed picks the coordinator and process ids up from the TPU
# metadata automatically, so no torchrun-style rendezvous flags are needed;
# utils.initialize_distributed() (called by every launcher) is a no-op on
# one host and brings the pod up on many.
#
# The mesh spans all hosts: 32 chips (v5e-32) below give TP=8 within hosts
# and DP=4 across them — BASELINE.md's north-star topology.  Shardings ride
# ICI within a host-block and DCN across; the mesh device order
# (parallel/mesh.py multi-slice layout) keeps tp/cp/kvr axes on ICI.
set -euo pipefail

REPO="$(cd "$(dirname "$0")/../../.." && pwd)"
cd "$REPO"

: "${PRESET:=llama2_7b}"
: "${TP:=8}"
: "${BATCH:=256}"          # global batch, split over dp automatically
: "${SEQ:=4096}"
: "${STEPS:=1000}"
: "${DATA:=}"              # NXDT token file (synthetic when empty)
: "${CKPT_DIR:=}"

ARGS=(
  --preset "$PRESET" --tp "$TP"
  --batch-size "$BATCH" --seq-len "$SEQ" --steps "$STEPS"
  --attention flash --loss-chunk 512
)
[[ -n "$DATA" ]] && ARGS+=(--data "$DATA")
[[ -n "$CKPT_DIR" ]] && ARGS+=(--ckpt-dir "$CKPT_DIR" --ckpt-every 100 --resume)

exec python examples/training/llama_pretrain.py "${ARGS[@]}"
