"""Token-exact perplexity evaluation over a token file.

The eval counterpart to the pretrain launchers: streams a ``data.loader``
token file through a jitted loss-sum step and reports
``exp(sum loss / sum tokens)`` — the exact corpus perplexity, not a
mean-of-batch-means (the same ``(loss_sum, tok)`` contract the trainer's
grad accumulation uses).  Reference analogue: the eval loops the examples
drive through ``NxDModel.run_eval`` (``trainer/model.py:30-39``).

Usage:
  python examples/eval_perplexity.py --data /tmp/tokens.bin --preset tiny \
      --tp 2 --batch 8 --seq 128
  python examples/eval_perplexity.py --data corpus.bin --preset llama2_7b \
      --tp 8 --ckpt /ckpts/run1          # newest tag

Prints ONE JSON line:
  {"metric": "eval_perplexity", "value": ..., "loss": ..., "tokens": N}
"""

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


def main() -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--data", required=True, help="token file (data.write_token_file)")
    p.add_argument("--family", default="llama",
                   choices=["llama", "gemma", "gemma2"])
    p.add_argument("--preset", default="tiny",
                   help="config preset on the family's Config class "
                        "(e.g. tiny, llama2_7b, gemma_7b, gemma2_9b)")
    p.add_argument("--tp", type=int, default=1)
    p.add_argument("--batch", type=int, default=8)
    p.add_argument("--seq", type=int, default=128)
    p.add_argument("--max-batches", type=int, default=0, help="0 = whole file")
    p.add_argument("--ckpt", default=None, help="checkpoint dir (orbax)")
    p.add_argument("--tag", default=None, help="checkpoint tag (default newest)")
    p.add_argument("--virtual-devices", type=int, default=None,
                   help="force an N-device virtual CPU mesh (dev/test runs)")
    args = p.parse_args()

    if args.virtual_devices:
        from neuronx_distributed_tpu.utils.common import ensure_virtual_devices

        ensure_virtual_devices(args.virtual_devices)

    import jax
    import jax.numpy as jnp

    import neuronx_distributed_tpu as nxd
    from neuronx_distributed_tpu.data import TokenDataLoader, TokenDataset
    from neuronx_distributed_tpu.models import (
        Gemma2Config,
        Gemma2ForCausalLM,
        GemmaConfig,
        GemmaForCausalLM,
        causal_lm_loss_sum,
    )
    from neuronx_distributed_tpu.models.llama import LlamaConfig, LlamaForCausalLM
    from neuronx_distributed_tpu.trainer import (
        default_batch_spec,
        initialize_parallel_model,
        load_checkpoint,
    )

    nxd.initialize_model_parallel(tensor_parallel_size=args.tp)
    on_tpu = jax.default_backend() == "tpu"
    cfg_cls, model_cls = {
        "llama": (LlamaConfig, LlamaForCausalLM),
        "gemma": (GemmaConfig, GemmaForCausalLM),
        "gemma2": (Gemma2Config, Gemma2ForCausalLM),
    }[args.family]
    cfg = getattr(cfg_cls, args.preset)(
        max_seq_len=args.seq,
        sequence_parallel=args.tp > 1,
        remat="none",
        attention_impl="flash" if on_tpu else "dense",
        dtype=jnp.bfloat16 if on_tpu else jnp.float32,
        param_dtype=jnp.float32,
    )
    config = nxd.training_config(tensor_parallel_size=args.tp)
    model = initialize_parallel_model(
        config, lambda: model_cls(cfg), (jnp.zeros((1, args.seq), jnp.int32),)
    )
    params = model.params
    if args.ckpt:
        model_state, _, _, _ = load_checkpoint(
            args.ckpt, tag=args.tag, model_template=model)
        params = model_state

    from jax.sharding import NamedSharding

    spec = NamedSharding(model.mesh, default_batch_spec())

    @jax.jit
    def eval_step(params, batch):
        loss_sum, tok = causal_lm_loss_sum(model.module, params, batch, None)
        return loss_sum.astype(jnp.float64 if jax.config.jax_enable_x64
                               else jnp.float32), tok

    ds = TokenDataset(args.data)
    loader = TokenDataLoader(ds, args.batch, args.seq, seed=0)
    total_sum, total_tok, batches = 0.0, 0, 0
    for batch in loader:
        batch = {k: jax.device_put(jnp.asarray(v), spec) for k, v in batch.items()}
        loss_sum, tok = eval_step(params, batch)
        total_sum += float(loss_sum)
        total_tok += int(tok)
        batches += 1
        if args.max_batches and batches >= args.max_batches:
            break
    loader.close()
    if total_tok == 0:
        print(json.dumps({"metric": "eval_perplexity", "value": float("nan"),
                          "loss": float("nan"), "tokens": 0}))
        return 1
    mean = total_sum / total_tok
    import math

    print(json.dumps({"metric": "eval_perplexity",
                      "value": round(math.exp(mean), 4),
                      "loss": round(mean, 6), "tokens": total_tok,
                      "batches": batches}))
    return 0


if __name__ == "__main__":
    sys.exit(main())
